"""Inference engine tests: C++ batcher, paged-KV correctness vs a
full-forward oracle, continuous batching, and the serving integration."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.serving.engine import Engine, EngineConfig
from kubeflow_tpu.serving.engine import model as M
from kubeflow_tpu.serving.engine.native import NativeBatcher
from kubeflow_tpu.serving.engine.serve import ByteTokenizer, JetStreamModel, VocabTokenizer

CFG = M.DecoderConfig(vocab_size=101, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128)


@pytest.fixture(scope="module")
def params():
    return M.init(jax.random.PRNGKey(0), CFG)


def greedy_oracle(params, prompt, n):
    toks = list(prompt)
    for _ in range(n):
        logits = M.forward_full(params, CFG, jnp.asarray([toks], jnp.int32))
        toks.append(int(np.asarray(logits)[0, -1].argmax()))
    return toks[len(prompt):]


def assert_greedy_equivalent(params, prompt, got, tie_eps=1e-3):
    """Greedy parity modulo bf16 argmax ties: follow the ENGINE's trajectory
    and require each emitted token's oracle logit to be within ``tie_eps``
    of the oracle max at that step.  The engine's prefill path (padded,
    batched) legally reorders bf16 reductions, so two exactly-tied logits
    can argmax to different indices — a numeric non-difference that an exact
    token comparison misreads as divergence."""
    toks = list(prompt)
    for g in got:
        logits = np.asarray(
            M.forward_full(params, CFG, jnp.asarray([toks], jnp.int32)))[0, -1]
        top = float(logits.max())
        assert float(logits[g]) >= top - tie_eps, (
            f"token {g} (logit {float(logits[g]):.4f}) not tied with oracle "
            f"argmax {int(logits.argmax())} (logit {top:.4f}) at step {len(toks) - len(prompt)}")
        toks.append(g)


# ------------------------------------------------------------------ C++ core


def test_native_batcher_lifecycle():
    b = NativeBatcher(max_slots=2, num_pages=9, page_size=4, max_pages_per_slot=4)
    # page 0 reserved: 8 usable
    assert b.free_pages == 8
    assert b.submit(1, 6, 4)        # needs 2 pages for prompt
    assert not b.submit(2, 20, 4)   # 24 tokens > 4 pages/slot cap: rejected
    slot, rid, plen, mnew, cached = b.admit()
    assert cached == 0
    assert (rid, plen, mnew) == (1, 6, 4) and b.free_pages == 6
    assert b.seq_lens()[slot] == 6
    assert 0 not in set(b.page_table()[slot][:2])  # trash page never allocated
    # token 7 crosses into page 2 (already covers 8), token 9 allocates page 3
    assert b.commit_token(slot, False) == 1
    assert b.commit_token(slot, False) == 1
    assert b.commit_token(slot, False) == 1
    assert b.free_pages == 5
    assert b.commit_token(slot, False) == 0  # max_new_tokens=4 exhausted
    b.release(slot)
    assert b.free_pages == 8 and b.num_active == 0
    b.close()


def test_native_batcher_raises_after_close():
    """Accessors on a closed batcher must raise a clean Python error, not
    pass NULL into the C core (segfault)."""
    b = NativeBatcher(max_slots=1, num_pages=8, page_size=4, max_pages_per_slot=4)
    b.close()
    for call in (lambda: b.cache_stats(), lambda: b.page_table(),
                 lambda: b.free_pages, lambda: b.num_active,
                 lambda: b.seq_lens(), lambda: b.submit(1, 4, 2)):
        with pytest.raises(RuntimeError, match="closed"):
            call()
    b.close()  # idempotent


def test_cancel_queued_with_reentrant_done_callback(params):
    """A Future done-callback that re-enters the engine (stats takes no lock
    but cancel-era code resolved under _lock) must not deadlock cancel()."""
    eng = Engine(params, CFG, EngineConfig(max_slots=1, num_pages=32,
                                           page_size=8, max_pages_per_slot=8))
    # engine NOT started: the request stays queued, exercising the
    # resolve-immediately path in cancel()
    fut = eng.generate_async([5, 7, 9], 4)
    seen = []
    fut.add_done_callback(lambda f: seen.append(eng.cancel(fut)))  # re-enters _lock
    assert eng.cancel(fut)
    assert fut.result(timeout=5)["cancelled"]
    assert seen == [False]  # the re-entrant cancel found the request gone
    eng.batcher.close()


def test_native_batcher_commit_token_ex_reports_page_grants():
    """commit_token_ex reports each newly-allocated page so callers can
    mirror the page table incrementally; the mirror must equal the full
    snapshot at every step."""
    b = NativeBatcher(max_slots=1, num_pages=16, page_size=4, max_pages_per_slot=8)
    assert b.submit(1, 6, 10)
    slot, *_ = b.admit()
    mirror = b.slot_pages(slot).copy()
    np.testing.assert_array_equal(mirror, b.page_table()[slot])
    seq = 6
    while True:
        rc, new_page = b.commit_token_ex(slot, False)
        if rc != 1:
            break
        seq += 1
        if new_page >= 0:
            mirror[(seq + 3) // 4 - 1] = new_page
        np.testing.assert_array_equal(mirror, b.page_table()[slot])
        assert b.seq_lens()[slot] == seq
    b.close()


def test_native_batcher_reclaimable_counter_matches_recompute():
    """The incremental reclaimable counter (admission's O(1) check) must
    track the O(cache) recompute through cache churn: insert, adopt, evict."""
    b = NativeBatcher(max_slots=2, num_pages=8, page_size=4, max_pages_per_slot=6)

    def check():
        assert b.reclaimable() == b.reclaimable_slow()

    h = np.arange(1, 4, dtype=np.uint64) * 1000  # 3-page chain
    assert b.submit(1, 12, 2, h[:2])
    slot, *_ = b.admit()
    check()
    b.release(slot, h)      # 3 pages cached, no external owner
    check()
    assert b.submit(2, 12, 2, h[:2])   # adopts 2 cached pages
    slot2, _, _, _, cached = b.admit()
    assert cached == 2
    check()                  # adopted pages block themselves + ancestors
    b.release(slot2, h)
    check()
    # pressure: 7 usable pages, 3 cached -> a 6-page prompt forces evictions
    assert b.submit(3, 21, 2)
    slot3, *_ = b.admit()
    assert b.cache_stats()["evictions"] > 0
    check()
    b.release(slot3)
    check()
    b.close()


def test_native_batcher_rejects_pool_unfittable_prompt():
    # per-slot cap (64) would admit it, but the whole pool has 31 usable
    # pages: queueing it would block head-of-line admission forever
    b = NativeBatcher(max_slots=2, num_pages=32, page_size=8, max_pages_per_slot=64)
    assert not b.submit(1, 300, 4)   # 38 pages > 32-page pool
    assert not b.submit(3, 256, 4)   # exactly 32 pages: page 0 reserved, still unfittable
    assert b.submit(2, 100, 4)       # 13 pages: fits the pool
    b.close()


@pytest.mark.slow
def test_chunked_prefill_long_prompt_matches_oracle(params):
    """A prompt longer than prefill_chunk is prefilled in page-aligned chunks
    (interleaved with decode); the generation must still equal the oracle,
    including while a short request decodes concurrently."""
    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16,
        prefill_chunk=32,
    ))
    eng.start()
    try:
        long_prompt = [(i * 7) % (CFG.vocab_size - 1) + 1 for i in range(75)]
        short_prompt = [5, 7, 9]
        f_long = eng.generate_async(long_prompt, 5)
        f_short = eng.generate_async(short_prompt, 5)
        assert f_long.result(timeout=180)["tokens"] == greedy_oracle(params, long_prompt, 5)
        assert f_short.result(timeout=180)["tokens"] == greedy_oracle(params, short_prompt, 5)
    finally:
        eng.stop()


def test_native_batcher_gang_admission_waits_for_pages():
    b = NativeBatcher(max_slots=2, num_pages=5, page_size=4, max_pages_per_slot=4)
    assert b.submit(1, 12, 1)  # 3 pages
    assert b.submit(2, 8, 1)   # 2 pages — only 1 free after req 1
    s1 = b.admit()
    assert s1 is not None
    assert b.admit() is None  # all-or-nothing: waits for pages
    b.release(s1[0])
    assert b.admit() is not None
    b.close()


# -------------------------------------------------------------- paged decode


def test_paged_decode_matches_full_forward(params):
    page_size = 8
    k_pool = jnp.zeros((CFG.n_layers, 16, CFG.n_kv_heads, page_size, CFG.head_dim), jnp.bfloat16)
    v_pool = jnp.zeros_like(k_pool)
    toks = np.array([[5, 7, 9, 11, 2, 4, 6, 8, 10, 3, 1, 12]], np.int32)
    full = np.asarray(M.forward_full(params, CFG, jnp.asarray(toks)))

    plen = 8
    logits, pk, pv = M.prefill(params, CFG, jnp.asarray(toks[:, :plen]), jnp.int32(plen), page_size)
    np.testing.assert_allclose(np.asarray(logits)[0], full[0, plen - 1], rtol=2e-2, atol=2e-2)

    # prefill returns batched [L, B, n_pages, ...]; row 0 is the prompt
    page_ids = jnp.asarray([3, 5], jnp.int32)
    k_pool, v_pool = M.write_pages(k_pool, v_pool, pk[:, 0], pv[:, 0], page_ids)
    B, max_pages = 3, 4
    pt = np.zeros((B, max_pages), np.int32)
    pt[1, :2] = [3, 5]
    seq = plen
    for t in range(plen, toks.shape[1]):
        if seq % page_size == 0:
            pt[1, seq // page_size] = 7
        tok = np.zeros((B,), np.int32)
        tok[1] = toks[0, t]
        seq += 1
        lens = np.zeros((B,), np.int32)
        lens[1] = seq
        logits, k_pool, v_pool = M.decode_step(
            params, CFG, jnp.asarray(tok), jnp.asarray(lens), jnp.asarray(pt), k_pool, v_pool
        )
        np.testing.assert_allclose(np.asarray(logits)[1], full[0, t], rtol=5e-2, atol=5e-2)


# ------------------------------------------------------------------- engine


@pytest.fixture()
def engine(params):
    eng = Engine(params, CFG, EngineConfig(max_slots=4, num_pages=64, page_size=8, max_pages_per_slot=16))
    eng.start()
    yield eng
    eng.stop()


def test_continuous_batching_matches_oracle(params, engine):
    """6 concurrent requests on 4 slots: queueing + slot rotation, all
    generations must equal the sequential greedy oracle."""
    prompts = [[5, 7, 9, 11], [1, 2, 3, 4, 5, 6, 7, 8, 9, 10], [42],
               [13, 14, 15], [99, 98, 97, 96, 95], [7]]
    futs = [engine.generate_async(p, 6) for p in prompts]
    results = [f.result(timeout=180) for f in futs]
    for p, r in zip(prompts, results):
        assert r["tokens"] == greedy_oracle(params, p, 6), p
        assert r["ttft_s"] > 0 and r["latency_s"] >= r["ttft_s"]
    assert engine.stats["active_slots"] == 0
    assert engine.stats["queue_depth"] == 0


def test_engine_rejects_oversized_prompt(engine):
    with pytest.raises(ValueError):
        engine.generate_async(list(range(1000)), 1000)  # > pages/slot capacity


def test_engine_page_cap_truncates(params):
    """A generation hitting the per-slot page cap finishes (truncated), it
    must not deadlock the pool."""
    eng = Engine(params, CFG, EngineConfig(max_slots=2, num_pages=32, page_size=4, max_pages_per_slot=3))
    eng.start()
    try:
        r = eng.generate([1, 2, 3, 4, 5], 100)  # 5+100 > 12 tokens/slot? rejected
    except ValueError:
        r = eng.generate([1, 2, 3], 9)  # exactly at cap: 3+9 = 12 = 3 pages
        assert r["num_tokens"] == 9
    finally:
        eng.stop()


# ---------------------------------------------------------------- tokenizers


def test_tokenizers(tmp_path):
    bt = ByteTokenizer()
    assert bt.decode(bt.encode("hello")) == "hello"
    vt = VocabTokenizer({"he": 0, "llo": 1, "l": 2, "o": 3, " ": 4})
    assert vt.encode("hello") == [0, 1]
    assert vt.decode([0, 1]) == "hello"


def test_jetstream_model_serving(params, tmp_path):
    """JetStreamModel end-to-end through the kserve Model interface."""
    eng = Engine(params, CFG, EngineConfig(max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16))
    m = JetStreamModel("llm", engine=eng)
    m.load()
    try:
        out = m.predict({"instances": [{"prompt": "ab", "max_tokens": 4}, "cd"]})
        assert len(out) == 2
        ids = ByteTokenizer().encode("ab")
        # greedy-equivalent, not token-exact: this prompt's first-step top-2
        # logits are an exact bf16 tie (2.2188 vs 2.2188), which the padded
        # prefill path resolves to the other index than the full forward
        assert_greedy_equivalent(params, ids, out[0]["token_ids"])
        assert out[0]["tokens"] == 4 and out[1]["tokens"] == 32
    finally:
        eng.stop()


def test_jetstream_model_from_dir(tmp_path):
    """Loader path: config.json + engine.json in the model dir."""
    d = tmp_path / "llm"
    d.mkdir()
    (d / "config.json").write_text(json.dumps(
        {"vocab_size": 64, "d_model": 32, "n_layers": 1, "n_heads": 2, "n_kv_heads": 1, "d_ff": 64}))
    (d / "engine.json").write_text(json.dumps({"max_slots": 2, "num_pages": 32, "page_size": 8}))
    m = JetStreamModel("tiny", str(d))
    m.load()
    try:
        out = m.predict({"instances": [{"prompt": "a", "max_tokens": 3}]})
        assert out[0]["tokens"] == 3
    finally:
        m.engine.stop()


# ------------------------------------------------------------ paged kernel

def test_paged_attention_kernel_matches_reference():
    """Pallas paged-decode attention == reference softmax over gathered
    pages, incl. GQA grouping, partial last pages, and inactive slots."""
    from kubeflow_tpu.serving.engine.paged_attention import paged_decode_attention

    rng = np.random.default_rng(0)
    B, Hq, Hkv, hd, ps, P, max_pages = 3, 4, 2, 16, 8, 12, 3
    q = jnp.asarray(rng.standard_normal((B, Hq, hd)), jnp.float32)
    k_pool = jnp.asarray(rng.standard_normal((P, Hkv, ps, hd)), jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((P, Hkv, ps, hd)), jnp.float32)
    page_table = jnp.asarray([[3, 5, 7], [1, 2, 0], [0, 0, 0]], jnp.int32)
    seq_lens = jnp.asarray([20, 9, 0], jnp.int32)  # partial pages; slot 2 idle

    out = np.asarray(paged_decode_attention(q, k_pool, v_pool, page_table,
                                            seq_lens, ps, interpret=True))

    # reference: gather + dense masked softmax per slot ([P,Hkv,ps,hd] pool
    # gathers to [MP,Hkv,ps,hd]; token-major cache needs the transpose)
    group = Hq // Hkv
    T = max_pages * ps
    for b in range(B):
        kc = np.asarray(k_pool)[np.asarray(page_table)[b]].transpose(0, 2, 1, 3).reshape(T, Hkv, hd)
        vc = np.asarray(v_pool)[np.asarray(page_table)[b]].transpose(0, 2, 1, 3).reshape(T, Hkv, hd)
        for h in range(Hq):
            kv_h = h // group
            logits = np.asarray(q)[b, h] @ kc[:, kv_h].T / np.sqrt(hd)
            m = np.arange(T) < int(seq_lens[b])
            if not m.any():
                np.testing.assert_allclose(out[b, h], 0.0, atol=1e-6)
                continue
            e = np.exp(logits[m] - logits[m].max())
            ref = (e / e.sum()) @ vc[m, kv_h]
            np.testing.assert_allclose(out[b, h], ref, rtol=1e-5, atol=1e-5)


def test_decode_step_paged_matches_gather(params):
    """decode_step(paged=True) produces the same logits as the XLA gather
    path on identical pool state."""
    page_size = 8
    shape = (CFG.n_layers, 16, CFG.n_kv_heads, page_size, CFG.head_dim)
    rng = np.random.default_rng(1)
    k0 = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    v0 = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    pt = jnp.asarray([[3, 5, 0, 0], [7, 0, 0, 0]], jnp.int32)
    lens = jnp.asarray([11, 5], jnp.int32)
    toks = jnp.asarray([42, 7], jnp.int32)

    k1, v1 = jnp.array(k0), jnp.array(v0)  # copies: decode_step donates pools
    lg, _, _ = M.decode_step(params, CFG, toks, lens, pt, k0, v0)
    lp, _, _ = M.decode_step(params, CFG, toks, lens, pt, k1, v1, paged=True)
    np.testing.assert_allclose(np.asarray(lg), np.asarray(lp), rtol=2e-2, atol=2e-2)


def test_engine_paged_kernel_env_gate(params, monkeypatch):
    """ENGINE_PAGED_KERNEL=1: full engine run through the Pallas decode path
    matches the greedy oracle."""
    monkeypatch.setenv("ENGINE_PAGED_KERNEL", "1")
    eng = Engine(params, CFG, EngineConfig(max_slots=2, num_pages=64, page_size=8,
                                           max_pages_per_slot=16))
    eng.start()
    try:
        prompts = [[5, 7, 9, 11], [1, 2, 3]]
        futs = [eng.generate_async(p, 5) for p in prompts]
        for p, f in zip(prompts, futs):
            assert f.result(timeout=180)["tokens"] == greedy_oracle(params, p, 5)
    finally:
        eng.stop()


# -------------------------------------------------------- tensor parallel

@pytest.mark.slow
def test_tensor_parallel_engine_matches_oracle(params):
    """TP serving (SURVEY.md §2c TP row): params + KV pool sharded over a
    2-device GSPMD mesh; generations must equal the single-device oracle and
    the big weights must actually be split across devices."""
    from jax.sharding import NamedSharding

    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16,
        tensor_parallel=2, prefill_chunk=32,
    ))
    # weights really are distributed: each device holds half of w1's columns
    w1 = eng.params["w1"]
    assert isinstance(w1.sharding, NamedSharding)
    assert w1.sharding.shard_shape(w1.shape)[2] == CFG.d_ff // 2
    kp = eng.k_pool
    assert kp.sharding.shard_shape(kp.shape)[2] == CFG.n_kv_heads // 2

    eng.start()
    try:
        prompts = [[5, 7, 9, 11], [(i * 7) % 97 + 1 for i in range(40)]]
        futs = [eng.generate_async(p, 5) for p in prompts]
        for p, f in zip(prompts, futs):
            assert f.result(timeout=180)["tokens"] == greedy_oracle(params, p, 5), p
    finally:
        eng.stop()


def test_tensor_parallel_rejects_indivisible_heads(params):
    with pytest.raises(ValueError, match="divide"):
        Engine(params, CFG, EngineConfig(max_slots=2, num_pages=32, page_size=8,
                                         max_pages_per_slot=8, tensor_parallel=3))


# ---------------------------------------------------------- prefix cache

def _drain(eng):
    """Wait until the engine loop has no in-flight work."""
    import time
    for _ in range(200):
        if not eng._requests and eng.batcher.num_active == 0:
            return
        time.sleep(0.02)
    raise TimeoutError("engine did not drain")


def test_prefix_cache_reuses_pages_and_matches_oracle(params):
    """vLLM/JetStream-style automatic prefix caching: a finished prompt's
    full pages stay in the pool; a second request sharing the prefix adopts
    them (page hits > 0) and must still generate the oracle-exact tokens."""
    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16,
        prefill_chunk=16,
    ))
    eng.start()
    try:
        prompt = [(i * 5) % (CFG.vocab_size - 1) + 1 for i in range(40)]
        first = eng.generate(prompt, 4, timeout=180)
        _drain(eng)
        stats = eng.stats
        # 40 tokens / 8 per page = 5 full pages now cached
        assert stats["cached_pages"] == 5
        assert stats["page_hits"] == 0

        # identical prompt: lookup eligibility is (40-1)//8 = 4 pages
        second = eng.generate(prompt, 4, timeout=180)
        assert second["tokens"] == first["tokens"] == greedy_oracle(params, prompt, 4)
        assert eng.stats["page_hits"] == 4

        # shared-prefix extension: same first 40 tokens + a new tail
        extended = prompt + [3, 1, 4, 1, 5]
        third = eng.generate(extended, 4, timeout=180)
        assert third["tokens"] == greedy_oracle(params, extended, 4)
        assert eng.stats["page_hits"] == 9  # +5: every full page of `prompt`
    finally:
        eng.stop()


@pytest.mark.slow
def test_prefix_cache_concurrent_shared_prefix(params):
    """Two in-flight requests sharing cached prefix pages must not corrupt
    each other (shared pages are read-only by construction)."""
    eng = Engine(params, CFG, EngineConfig(
        max_slots=4, num_pages=64, page_size=8, max_pages_per_slot=16,
        prefill_chunk=16,
    ))
    eng.start()
    try:
        base = [(i * 11) % (CFG.vocab_size - 1) + 1 for i in range(24)]
        eng.generate(base, 2, timeout=180)  # seed the cache
        _drain(eng)
        exts = [base + [7, 7], base + [9, 9, 9], base]
        futs = [eng.generate_async(p, 4) for p in exts]
        for p, f in zip(exts, futs):
            assert f.result(timeout=180)["tokens"] == greedy_oracle(params, p, 4), p
        assert eng.stats["page_hits"] > 0
    finally:
        eng.stop()


def _greedy_tie_aware_check(params, prompt, generated):
    """Assert every generated token is a max-logit token given the engine's
    own prefix: bf16 logits can tie exactly, and argmax tie-break order is
    allowed to differ between the paged path and the full-forward oracle."""
    toks = list(prompt)
    for tok in generated:
        logits = np.asarray(M.forward_full(params, CFG, jnp.asarray([toks], jnp.int32)))[0, -1]
        assert logits[tok] == logits.max(), (toks, tok)
        toks.append(tok)


def test_prefix_cache_evicts_under_pressure(params):
    """Cached pages must never cause admissions to fail: distinct prompts
    that together exceed the pool evict stale cache entries (leaf-first LRU)
    and every request still completes."""
    eng = Engine(params, CFG, EngineConfig(
        max_slots=1, num_pages=9, page_size=8, max_pages_per_slot=8,
        prefill_chunk=16,
    ))
    eng.start()
    try:
        for seed in range(5):
            prompt = [(seed * 31 + i * 3) % (CFG.vocab_size - 1) + 1 for i in range(24)]
            out = eng.generate(prompt, 3, timeout=180)
            _greedy_tie_aware_check(params, prompt, out["tokens"])
        stats = eng.stats
        assert stats["evictions"] > 0
        # pool invariant: free + cached + trash == num_pages
        assert stats["free_pages"] + stats["cached_pages"] == 9 - 1
    finally:
        eng.stop()


def test_native_batcher_prefix_pin_and_adopt():
    """Core-level: release-with-hashes caches pages; a later submit pins the
    chain prefix and admit adopts it without allocating those pages."""
    b = NativeBatcher(max_slots=2, num_pages=9, page_size=4, max_pages_per_slot=8)
    hashes = np.array([11, 22, 33], np.uint64)  # 3 full prompt pages
    assert b.submit(1, 12, 1, hashes[:2])
    s = b.admit()
    assert s is not None and s[4] == 0  # nothing cached yet
    pages_before = list(b.page_table()[s[0]][:3])
    assert b.commit_token(s[0], True) == 0
    b.release(s[0], hashes)
    assert b.cache_stats()["cached_pages"] == 3
    assert b.free_pages == 8 - 3

    # same 12-token prompt: 2 of 3 pages are lookup-eligible, both hit
    assert b.submit(2, 12, 1, hashes[:2])
    s2 = b.admit()
    assert s2 is not None and s2[4] == 2
    assert list(b.page_table()[s2[0]][:2]) == pages_before[:2]
    assert b.commit_token(s2[0], True) == 0
    b.release(s2[0], hashes)
    # the same chain re-released: no duplicate entries, refs balanced
    assert b.cache_stats()["cached_pages"] == 3
    assert b.free_pages == 8 - 3
    b.close()


def test_native_batcher_queued_cache_sharer_cannot_deadlock_admission():
    """Regression (r2 review): a queued request whose prefix is cached must
    not block an earlier request that needs those pages.  Lookup happens at
    admit (not submit), so the cache stays evictable and head-of-line
    admission always makes progress; eviction is leaf-first, so the
    surviving prefix is still useful to the sharer."""
    b = NativeBatcher(max_slots=1, num_pages=9, page_size=4, max_pages_per_slot=8)
    ha = np.array([1, 2, 3, 4, 5, 6], np.uint64)
    assert b.submit(1, 24, 1, ha[:5])  # A: 6 pages
    sa = b.admit()
    assert b.commit_token(sa[0], True) == 0
    b.release(sa[0], ha)               # A's 6 pages now cached; free = 2
    assert b.cache_stats()["cached_pages"] == 6 and b.free_pages == 2

    assert b.submit(2, 16, 1)          # B: needs 4 fresh pages (head of line)
    assert b.submit(3, 24, 1, ha[:5])  # C: shares A's prefix, queued behind B
    sb = b.admit()                     # must evict 2 cached leaves for B
    assert sb is not None and sb[1] == 2
    assert b.cache_stats()["evictions"] == 2
    # B's one generated token grows into a 5th page: free list is empty, so a
    # third cache leaf is evicted on the commit path
    assert b.commit_token(sb[0], True) == 0
    b.release(sb[0])
    assert b.cache_stats()["evictions"] == 3

    sc = b.admit()                     # C: the surviving 3-page prefix hits
    assert sc is not None and sc[1] == 3 and sc[4] == 3
    b.release(sc[0])
    b.close()


# ------------------------------------------------------------ int8 KV cache

def test_int8_kv_pool_decode_logits_close_to_bf16(params):
    """Quantized pools must track the bf16 paged path closely: same prompt
    prefilled + decoded through both pool representations, logits compared."""
    page_size = 8
    toks = np.array([[5, 7, 9, 11, 2, 4, 6, 8, 10, 3, 1, 12]], np.int32)
    plen = 8
    logits_ref = None
    for quant in (None, "int8"):
        k_pool = M.make_kv_pool((CFG.n_layers, 16, CFG.n_kv_heads, page_size, CFG.head_dim), quant)
        v_pool = M.make_kv_pool((CFG.n_layers, 16, CFG.n_kv_heads, page_size, CFG.head_dim), quant)
        _, pk, pv = M.prefill(params, CFG, jnp.asarray(toks[:, :plen]), jnp.int32(plen), page_size)
        k_pool, v_pool = M.write_pages(k_pool, v_pool, pk[:, 0], pv[:, 0],
                                       jnp.asarray([3, 5], jnp.int32))
        pt = np.zeros((2, 4), np.int32)
        pt[1, :2] = [3, 5]
        tok = np.zeros((2,), np.int32)
        tok[1] = toks[0, plen]
        lens = np.zeros((2,), np.int32)
        lens[1] = plen + 1
        logits, k_pool, v_pool = M.decode_step(
            params, CFG, jnp.asarray(tok), jnp.asarray(lens), jnp.asarray(pt), k_pool, v_pool)
        if quant is None:
            logits_ref = np.asarray(logits)[1]
        else:
            np.testing.assert_allclose(np.asarray(logits)[1], logits_ref, atol=0.15, rtol=0.05)


def test_engine_int8_kv_quant_generates_near_greedy(params):
    """E2E with kv_quant='int8': every generated token must be within a small
    logit margin of the full-precision oracle's argmax at each step (exact
    equality is not promised — int8 noise may flip near-ties)."""
    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16,
        prefill_chunk=16, kv_quant="int8",
    ))
    assert isinstance(eng.k_pool, dict) and eng.k_pool["q"].dtype == jnp.int8
    eng.start()
    try:
        for prompt in ([5, 7, 9, 11], [(i * 5) % (CFG.vocab_size - 1) + 1 for i in range(40)]):
            out = eng.generate(prompt, 4, timeout=180)
            toks = list(prompt)
            for tok in out["tokens"]:
                logits = np.asarray(M.forward_full(params, CFG, jnp.asarray([toks], jnp.int32)))[0, -1]
                assert logits.max() - logits[tok] <= 0.35, (toks, tok, float(logits.max() - logits[tok]))
                toks.append(tok)
    finally:
        eng.stop()


# ------------------------------------------------- feature composition
#
# VERDICT r2 #3: the four headline engine features must COMPOSE — a
# production JetStream-class config runs paged attention + TP + int8 KV +
# prefix cache (+ speculative) simultaneously.  The kernel-level tests run
# in interpret mode (cheap, exact); the E2E combos drive the full engine.


def test_paged_kernel_multi_query_matches_reference():
    """The K-query kernel (speculative verify): each query row's causal
    horizon is offset by its draft index — compare against a dense masked
    softmax per (slot, query, head)."""
    from kubeflow_tpu.serving.engine.paged_attention import paged_attention

    rng = np.random.default_rng(2)
    B, K, Hq, Hkv, hd, ps, P, max_pages = 2, 3, 4, 2, 16, 8, 12, 3
    q = jnp.asarray(rng.standard_normal((B, K, Hq, hd)), jnp.float32)
    k_pool = jnp.asarray(rng.standard_normal((P, Hkv, ps, hd)), jnp.float32)
    v_pool = jnp.asarray(rng.standard_normal((P, Hkv, ps, hd)), jnp.float32)
    page_table = jnp.asarray([[3, 5, 7], [1, 2, 0]], jnp.int32)
    seq_lens = jnp.asarray([18, 6], jnp.int32)  # draft rows extend past these

    out = np.asarray(paged_attention(q, k_pool, v_pool, page_table,
                                     seq_lens, ps, interpret=True))
    group = Hq // Hkv
    T = max_pages * ps
    for b in range(B):
        kc = np.asarray(k_pool)[np.asarray(page_table)[b]].transpose(0, 2, 1, 3).reshape(T, Hkv, hd)
        vc = np.asarray(v_pool)[np.asarray(page_table)[b]].transpose(0, 2, 1, 3).reshape(T, Hkv, hd)
        for j in range(K):
            horizon = int(seq_lens[b]) + j  # row j sees positions < len+j
            m = np.arange(T) < horizon
            for h in range(Hq):
                kv_h = h // group
                logits = np.asarray(q)[b, j, h] @ kc[:, kv_h].T / np.sqrt(hd)
                e = np.exp(logits[m] - logits[m].max())
                ref = (e / e.sum()) @ vc[m, kv_h]
                np.testing.assert_allclose(out[b, j, h], ref, rtol=1e-5, atol=1e-5)


def test_paged_kernel_int8_pool_matches_dequant_reference():
    """The kernel dequantizes {'q','s'} pools in place: result must equal the
    same computation over the host-dequantized pool."""
    from kubeflow_tpu.serving.engine.paged_attention import paged_decode_attention

    rng = np.random.default_rng(3)
    B, Hq, Hkv, hd, ps, P = 2, 4, 2, 16, 8, 10
    q = jnp.asarray(rng.standard_normal((B, Hq, hd)), jnp.float32)
    kq = jnp.asarray(rng.integers(-127, 128, (P, Hkv, ps, hd)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (P, Hkv, ps, hd)), jnp.int8)
    ks = jnp.asarray(rng.uniform(0.01, 0.1, (P, Hkv, ps, 1)), jnp.bfloat16)
    vs = jnp.asarray(rng.uniform(0.01, 0.1, (P, Hkv, ps, 1)), jnp.bfloat16)
    page_table = jnp.asarray([[3, 5], [1, 0]], jnp.int32)
    seq_lens = jnp.asarray([13, 8], jnp.int32)

    out = np.asarray(paged_decode_attention(
        q, {"q": kq, "s": ks}, {"q": vq, "s": vs}, page_table, seq_lens, ps,
        interpret=True))
    k_deq = (kq.astype(jnp.float32) * ks.astype(jnp.float32))
    v_deq = (vq.astype(jnp.float32) * vs.astype(jnp.float32))
    ref = np.asarray(paged_decode_attention(
        q, k_deq, v_deq, page_table, seq_lens, ps, interpret=True))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_decode_step_paged_int8_matches_gather_int8(params):
    """decode_step(paged=True) over a quantized pool == the XLA gather path
    over the SAME quantized pool (both dequantize identically)."""
    page_size = 8
    shape = (CFG.n_layers, 16, CFG.n_kv_heads, page_size, CFG.head_dim)
    toks8 = np.array([[5, 7, 9, 11, 2, 4, 6, 8]], np.int32)
    pools = []
    for _ in range(2):  # two identical quantized pools (decode_step donates)
        k_pool = M.make_kv_pool(shape, "int8")
        v_pool = M.make_kv_pool(shape, "int8")
        _, pk, pv = M.prefill(params, CFG, jnp.asarray(toks8), jnp.int32(8), page_size)
        k_pool, v_pool = M.write_pages(k_pool, v_pool, pk[:, 0], pv[:, 0],
                                       jnp.asarray([3], jnp.int32))
        pools.append((k_pool, v_pool))
    pt = jnp.asarray([[3, 0, 0, 0], [0, 0, 0, 0]], jnp.int32)
    lens = jnp.asarray([8, 0], jnp.int32)
    tok = jnp.asarray([10, 0], jnp.int32)
    lg, _, _ = M.decode_step(params, CFG, tok, lens, pt, *pools[0])
    lp, _, _ = M.decode_step(params, CFG, tok, lens, pt, *pools[1], paged=True)
    # int8-dequant feeding bf16 attention: observed worst-case deviation is
    # ~0.024 on logits of magnitude ~2 (one int8 quantization step times the
    # bf16 reduction-order slack), so 2e-2 was inside the noise floor
    np.testing.assert_allclose(np.asarray(lg)[0], np.asarray(lp)[0], rtol=5e-2, atol=5e-2)


def test_decode_step_k_paged_matches_gather(params):
    """Speculative verify through the Pallas kernel == the gather path on
    identical pool state (bf16)."""
    page_size = 8
    shape = (CFG.n_layers, 16, CFG.n_kv_heads, page_size, CFG.head_dim)
    rng = np.random.default_rng(4)
    k0 = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    v0 = jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
    k1, v1 = jnp.array(k0), jnp.array(v0)
    pt = jnp.asarray([[3, 5, 0, 0], [7, 0, 0, 0]], jnp.int32)
    lens = jnp.asarray([11, 4], jnp.int32)
    toks = jnp.asarray([[42, 17, 9], [7, 3, 0]], jnp.int32)
    lg, _, _ = M.decode_step_k(params, CFG, toks, lens, pt, k0, v0)
    lp, _, _ = M.decode_step_k(params, CFG, toks, lens, pt, k1, v1, paged=True)
    # the gather path multiplies softmax probs in bf16 (_attn casts); the
    # kernel keeps the f32 accumulator — tolerance covers that gap
    np.testing.assert_allclose(np.asarray(lg), np.asarray(lp), rtol=4e-2, atol=4e-2)


@pytest.mark.slow
def test_engine_paged_with_int8_kv_matches_near_greedy(params):
    """E2E paged kernel × int8 KV: generated tokens within the int8 logit
    margin of the full-precision oracle (same tolerance as the int8 test)."""
    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16,
        prefill_chunk=16, kv_quant="int8", paged_kernel=True,
    ))
    assert isinstance(eng.k_pool, dict)
    eng.start()
    try:
        for prompt in ([5, 7, 9, 11], [(i * 5) % (CFG.vocab_size - 1) + 1 for i in range(20)]):
            out = eng.generate(prompt, 4, timeout=180)
            toks = list(prompt)
            for tok in out["tokens"]:
                logits = np.asarray(M.forward_full(params, CFG, jnp.asarray([toks], jnp.int32)))[0, -1]
                assert logits.max() - logits[tok] <= 0.35, (toks, tok)
                toks.append(tok)
    finally:
        eng.stop()


@pytest.mark.slow
def test_engine_paged_with_tensor_parallel_matches_oracle(params):
    """E2E paged kernel × TP=2: the kernel runs per-shard under shard_map
    (heads independent); generations equal the single-device greedy oracle."""
    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16,
        tensor_parallel=2, paged_kernel=True, prefill_chunk=32,
    ))
    eng.start()
    try:
        prompts = [[5, 7, 9, 11], [(i * 7) % 97 + 1 for i in range(20)]]
        futs = [eng.generate_async(p, 5) for p in prompts]
        for p, f in zip(prompts, futs):
            assert f.result(timeout=180)["tokens"] == greedy_oracle(params, p, 5), p
    finally:
        eng.stop()


@pytest.mark.slow
def test_engine_speculative_with_paged_kernel_lossless(params):
    """E2E speculative × paged kernel: the multi-query verify runs through
    the Pallas kernel and stays lossless vs the greedy oracle, with drafts
    actually accepted."""
    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16,
        paged_kernel=True, speculative="prompt_lookup", spec_max_draft=4,
    ))
    eng.start()
    try:
        # repetitive prompt → the n-gram draft fires and accepts
        prompt = [3, 4, 5, 3, 4, 5, 3, 4]
        out = eng.generate(prompt, 8, timeout=180)
        assert out["tokens"] == greedy_oracle(params, prompt, 8)
        assert eng.stats["spec_proposed"] > 0
    finally:
        eng.stop()


@pytest.mark.slow
def test_engine_production_config_paged_tp_int8_prefix_cache(params):
    """The production JetStream-class config: paged kernel + TP=2 + int8 KV
    + prefix cache, all at once.  Tokens stay within the int8 margin of the
    oracle and the second shared-prefix request hits the cache."""
    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16,
        tensor_parallel=2, kv_quant="int8", paged_kernel=True,
        prefill_chunk=16,
    ))
    eng.start()
    try:
        prefix = [(i * 5) % (CFG.vocab_size - 1) + 1 for i in range(16)]
        out1 = eng.generate(prefix + [7], 4, timeout=180)
        hits0 = eng.stats["page_hits"]
        out2 = eng.generate(prefix + [9], 4, timeout=180)
        assert eng.stats["page_hits"] > hits0  # shared prefix adopted
        for prompt, out in ((prefix + [7], out1), (prefix + [9], out2)):
            toks = list(prompt)
            for tok in out["tokens"]:
                logits = np.asarray(M.forward_full(params, CFG, jnp.asarray([toks], jnp.int32)))[0, -1]
                assert logits.max() - logits[tok] <= 0.35, (toks, tok)
                toks.append(tok)
    finally:
        eng.stop()


# ------------------------------------------------------------- streaming

def test_engine_generate_stream_yields_tokens_incrementally(params, engine):
    """generate_stream yields each token as committed, then the result dict;
    the streamed ids must equal the unary result and the greedy oracle."""
    prompt = [5, 7, 9, 11]
    items = list(engine.generate_stream(prompt, 6, timeout=180))
    *tokens, final = items
    assert isinstance(final, dict) and final["num_tokens"] == 6
    assert tokens == final["tokens"] == greedy_oracle(params, prompt, 6)


def test_model_server_generate_and_sse_stream(params):
    """KServe/OIP LLM surface: unary /v2/models/x/generate and SSE
    /v2/models/x/generate_stream against a live HTTP server."""
    import urllib.request

    from kubeflow_tpu.serving.server import ModelServer

    eng = Engine(params, CFG, EngineConfig(max_slots=2, num_pages=64,
                                           page_size=8, max_pages_per_slot=16))
    m = JetStreamModel("llm", engine=eng)
    srv = ModelServer([m])
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}/v2/models/llm"
        body = json.dumps({"text_input": "ab", "parameters": {"max_tokens": 5}}).encode()

        req = urllib.request.Request(base + "/generate", data=body,
                                     headers={"Content-Type": "application/json"})
        unary = json.loads(urllib.request.urlopen(req, timeout=120).read())
        assert unary["model_name"] == "llm" and unary["tokens"] == 5

        req = urllib.request.Request(base + "/generate_stream", data=body,
                                     headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=120)
        assert resp.headers["Content-Type"] == "text/event-stream"
        events = [json.loads(line[len(b"data: "):])
                  for line in resp.read().split(b"\n\n") if line.startswith(b"data: ")]
        pieces = [e["text_output"] for e in events if not e.get("done")]
        assert len(pieces) == 5
        assert "".join(pieces) == unary["text_output"]
        assert events[-1].get("done") and events[-1]["tokens"] == 5
    finally:
        srv.stop()
        eng.stop()


# ------------------------------------------------------- speculative decode

@pytest.mark.slow
def test_speculative_prompt_lookup_is_lossless(params):
    """Prompt-lookup speculative decoding must produce EXACTLY the greedy
    oracle (acceptance only keeps tokens argmax would have produced), and a
    repetitive prompt must actually get drafts accepted."""
    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16,
        speculative="prompt_lookup", spec_max_draft=4, spec_ngram=1,
    ))
    eng.start()
    try:
        # a prompt containing EVERY vocab token: whatever the model
        # generates, the unigram lookup finds an earlier occurrence, so
        # drafts are proposed on every decode tick (with this random tiny
        # model the drafts are usually wrong — losslessness is the point)
        all_vocab = list(range(CFG.vocab_size))
        periodic = [7, 3, 9, 5] * 6
        for prompt in (all_vocab, periodic, [5, 7, 9]):
            out = eng.generate(prompt, 8, timeout=180)
            assert out["tokens"] == greedy_oracle(params, prompt, 8), prompt
        stats = eng.stats
        assert stats["spec_proposed"] > 0
    finally:
        eng.stop()


def test_speculative_with_int8_kv_and_prefix_cache(params):
    """Speculative decoding composes with int8 KV quantization and the
    prefix cache; generations stay within the quantization logit margin."""
    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16,
        speculative="prompt_lookup", kv_quant="int8",
    ))
    eng.start()
    try:
        prompt = [7, 3, 9, 5] * 8
        first = eng.generate(prompt, 6, timeout=180)
        second = eng.generate(prompt, 6, timeout=180)  # prefix-cache hit path
        assert first["tokens"] == second["tokens"]
        for r in (first, second):
            toks = list(prompt)
            for tok in r["tokens"]:
                logits = np.asarray(M.forward_full(params, CFG, jnp.asarray([toks], jnp.int32)))[0, -1]
                assert logits.max() - logits[tok] <= 0.35, (toks, tok)
                toks.append(tok)
        assert eng.stats["page_hits"] > 0
    finally:
        eng.stop()


def test_speculative_rejects_nonzero_temperature(params):
    with pytest.raises(ValueError, match="temperature"):
        Engine(params, CFG, EngineConfig(max_slots=2, num_pages=32, page_size=8,
                                         max_pages_per_slot=8, temperature=0.7,
                                         speculative="prompt_lookup"))


@pytest.mark.slow
def test_speculative_accepts_drafts_and_stays_lossless(params):
    """When the context's tail IS the model's own continuation (prompt =
    base + oracle(base)), the n-gram drafts match greedy and get ACCEPTED —
    multi-token commits per verify pass — and output still equals the
    oracle exactly."""
    base = [(i * 7) % CFG.vocab_size for i in range(12)]
    cont = greedy_oracle(params, base, 24)
    prompt = base + cont[:16]
    eng = Engine(params, CFG, EngineConfig(
        max_slots=1, num_pages=64, page_size=8, max_pages_per_slot=16,
        speculative="prompt_lookup", spec_ngram=2,
    ))
    eng.start()
    try:
        out = eng.generate(prompt, 8, timeout=180)
        assert out["tokens"] == greedy_oracle(params, prompt, 8)
        assert eng.stats["spec_accepted"] > 0
    finally:
        eng.stop()


def test_speculative_lossless_at_slot_capacity_edge(params):
    """Regression (r2 review): near slot capacity the verify step's PADDING
    rows index past the page table; they must route to the trash page, not
    clip onto the slot's last owned page (which would corrupt committed KV).
    prompt+max_new fills the slot to exactly T = max_pages*page_size."""
    base = [(i * 7) % CFG.vocab_size for i in range(12)]
    cont = greedy_oracle(params, base, 12)
    prompt = base + cont  # 24 tokens; + 8 generated == 32 == 4 pages * 8
    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=4,
        speculative="prompt_lookup", spec_ngram=1, spec_max_draft=4,
    ))
    eng.start()
    try:
        out = eng.generate(prompt, 8, timeout=180)
        assert out["tokens"] == greedy_oracle(params, prompt, 8)
    finally:
        eng.stop()


# --------------------------------------------------------- sanitizer stress

@pytest.mark.slow
@pytest.mark.parametrize("sanitizer", ["thread", "address"])
def test_core_concurrent_stress_under_sanitizers(sanitizer, tmp_path):
    """The `go test -race` stand-in (SURVEY.md §5): the C++ core's full API
    hammered from racing submitter/decoder/snapshot threads, compiled with
    TSAN/ASAN. Any report fails the test even if the binary exits 0."""
    import os
    import subprocess

    eng_dir = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                           "kubeflow_tpu", "serving", "engine")
    target = {"thread": "stress-tsan", "address": "stress-asan"}[sanitizer]
    # build through the Makefile target so the flags have one source of truth
    build = subprocess.run(["make", "-C", eng_dir, target],
                           capture_output=True, text=True, timeout=180)
    assert build.returncode == 0, build.stderr[-2000:]
    binary = os.path.join(eng_dir, target.replace("-", "_"))
    env = dict(os.environ)
    env["TSAN_OPTIONS"] = "halt_on_error=1"
    env["ASAN_OPTIONS"] = "detect_leaks=1"
    run = subprocess.run([str(binary)], capture_output=True, text=True,
                         timeout=400, env=env)
    report = run.stdout + run.stderr
    assert run.returncode == 0, report[-3000:]
    assert "stress OK" in run.stdout
    assert "WARNING: ThreadSanitizer" not in report
    assert "ERROR: AddressSanitizer" not in report and "LeakSanitizer" not in report


# ------------------------------------------------------------- cancellation

def test_cancel_queued_and_midflight_requests(params):
    """Engine.cancel: a queued request resolves cancelled without running; a
    mid-generation cancel stops early, frees the slot for waiting work, and
    other requests are untouched."""
    import time as _time

    eng = Engine(params, CFG, EngineConfig(
        max_slots=1, num_pages=64, page_size=8, max_pages_per_slot=16,
    ))
    eng.start()
    try:
        long_run = eng.generate_async([5, 7, 9], 120)     # hogs the only slot
        queued = eng.generate_async([1, 2, 3], 50)        # waits in queue
        follow = eng.generate_async([4, 4], 4)            # behind it
        assert eng.cancel(queued)  # cancelled while still in the C++ queue

        # let the long run commit a few tokens, then cancel it mid-flight
        # (guard on done() too: never spin if it somehow races to the end)
        while not long_run.done():
            with eng._lock:
                done_some = any(len(p.generated) >= 3 for p in eng._requests.values()
                                if p.future is long_run)
            if done_some:
                break
            _time.sleep(0.02)
        if eng.cancel(long_run):
            r = long_run.result(timeout=120)
            assert r["cancelled"] and 0 < r["num_tokens"] < 120
        else:  # raced to completion on a stalled box: still a valid outcome
            assert long_run.result(timeout=1)["num_tokens"] == 120

        q = queued.result(timeout=120)  # resolves at admission, having run nothing
        assert q["cancelled"] and q["num_tokens"] == 0

        # the follower proceeds and matches the oracle exactly
        assert follow.result(timeout=120)["tokens"] == greedy_oracle(params, [4, 4], 4)
        assert eng.stats["active_slots"] == 0
        assert not eng.cancel(long_run)  # already finished
    finally:
        eng.stop()


def test_stream_disconnect_cancels_request(params):
    """Abandoning a token stream (client disconnect) must free the slot
    instead of decoding to the budget for nobody."""
    eng = Engine(params, CFG, EngineConfig(
        max_slots=1, num_pages=64, page_size=8, max_pages_per_slot=16,
    ))
    m = JetStreamModel("llm", engine=eng)
    m.load()
    try:
        gen = m.generate_stream({"text_input": "abcd",
                                 "parameters": {"max_tokens": 100}})
        next(gen)        # at least one piece flowed
        gen.close()      # simulated disconnect -> GeneratorExit -> cancel
        # the slot must come free quickly (not after 100 tokens)
        import time as _time
        for _ in range(200):
            if eng.stats["active_slots"] == 0:
                break
            _time.sleep(0.05)
        assert eng.stats["active_slots"] == 0
        # the engine is still healthy for the next request
        out = eng.generate([1, 2], 3, timeout=120)
        assert out["tokens"] == greedy_oracle(params, [1, 2], 3)
    finally:
        eng.stop()


# ------------------------------------------------- int8 weight quantization


def test_weight_quant_int8_logits_close(params):
    """Weight-only int8 (per-output-channel scales): full-vocab logits must
    track bf16 within quantization noise on a forward pass."""
    qp = M.quantize_weights_int8(params)
    assert qp["wq"]["q"].dtype == jnp.int8 and qp["ln_attn"].dtype != jnp.int8
    toks = jnp.asarray([[5, 7, 9, 11, 13]], jnp.int32)
    ref = np.asarray(M.forward_full(params, CFG, toks))
    got = np.asarray(M.forward_full(qp, CFG, toks))
    # logits are O(1) for this init; int8 per-channel noise stays well inside
    denom = max(1.0, float(np.abs(ref).max()))
    assert np.abs(got - ref).max() / denom < 0.08, np.abs(got - ref).max()


def test_weight_quant_int8_halves_param_bytes(params):
    before = sum(x.nbytes for x in jax.tree.leaves(params))
    qp = M.quantize_weights_int8(params)
    after = sum(x.nbytes for x in jax.tree.leaves(qp))
    # int8 payload + bf16 scales ≈ half the bf16 bytes (scales are ~1/d_model)
    assert after < 0.6 * before, (before, after)


def test_engine_weight_quant_generates_near_greedy(params):
    """E2E with weight_quant='int8': generated tokens stay within a small
    logit margin of the full-precision oracle (int8 may flip near-ties)."""
    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16,
        prefill_chunk=16, weight_quant="int8",
    ))
    assert isinstance(eng.params["w1"], dict)
    eng.start()
    try:
        prompt = [5, 7, 9, 11]
        out = eng.generate(prompt, 4, timeout=180)
        toks = list(prompt)
        for tok in out["tokens"]:
            logits = np.asarray(M.forward_full(params, CFG, jnp.asarray([toks], jnp.int32)))[0, -1]
            assert logits.max() - logits[tok] <= 0.5, (toks, tok)
            toks.append(tok)
    finally:
        eng.stop()


def test_engine_weight_quant_with_tp_and_int8_kv(params):
    """Composition: weight_quant x tensor_parallel x kv_quant in one engine —
    quantized shards place on the mesh (scale singletons unsharded) and the
    engine still generates coherently."""
    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16,
        prefill_chunk=16, weight_quant="int8", kv_quant="int8",
        tensor_parallel=2,
    ))
    eng.start()
    try:
        out = eng.generate([3, 1, 4, 1, 5], 4, timeout=240)
        assert len(out["tokens"]) == 4
        assert all(0 <= t < CFG.vocab_size for t in out["tokens"])
    finally:
        eng.stop()


def test_model_server_openai_compat(params):
    """OpenAI-compatible surface (the KServe huggingface-runtime paths):
    /openai/v1/models, /completions (unary + SSE with [DONE]), and
    /chat/completions; usage token accounting filled in."""
    import urllib.request

    from kubeflow_tpu.serving.server import ModelServer

    eng = Engine(params, CFG, EngineConfig(max_slots=2, num_pages=64,
                                           page_size=8, max_pages_per_slot=16))
    m = JetStreamModel("llm", engine=eng)
    srv = ModelServer([m])
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}/openai/v1"
        models = json.loads(urllib.request.urlopen(base + "/models", timeout=30).read())
        assert models["data"][0]["id"] == "llm"

        def post(path, payload):
            req = urllib.request.Request(
                base + path, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            return urllib.request.urlopen(req, timeout=120)

        # unary completions; model omitted = the single served model
        out = json.loads(post("/completions",
                              {"prompt": "ab", "max_tokens": 4}).read())
        assert out["object"] == "text_completion" and out["model"] == "llm"
        assert out["choices"][0]["finish_reason"] == "length"
        assert out["usage"]["completion_tokens"] == 4
        assert out["usage"]["total_tokens"] == out["usage"]["prompt_tokens"] + 4

        # chat: role-tagged template, assistant message back
        chat = json.loads(post("/chat/completions", {
            "model": "llm", "max_tokens": 3,
            "messages": [{"role": "system", "content": "be brief"},
                         {"role": "user", "content":
                          [{"type": "text", "text": "hi"}]}]}).read())
        assert chat["object"] == "chat.completion"
        assert chat["choices"][0]["message"]["role"] == "assistant"
        assert isinstance(chat["choices"][0]["message"]["content"], str)

        # chat streaming: first chunk's delta carries the assistant role
        resp = post("/chat/completions", {
            "model": "llm", "max_tokens": 3, "stream": True,
            "messages": [{"role": "user", "content": "hi"}]})
        raw = [line[len(b"data: "):] for line in resp.read().split(b"\n\n")
               if line.startswith(b"data: ")]
        assert raw[-1] == b"[DONE]"
        first = json.loads(raw[0])
        assert first["choices"][0]["delta"]["role"] == "assistant"

        # OpenAI nullable max_tokens and bad values -> envelope errors
        out2 = json.loads(post("/completions",
                               {"prompt": "ab", "max_tokens": None}).read())
        assert out2["usage"]["completion_tokens"] <= 16
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as e:
            post("/completions", {"prompt": "ab", "max_tokens": "abc"})
        assert e.value.code == 400

        # streaming: delta chunks then [DONE]; concatenation == unary text
        resp = post("/completions", {"prompt": "ab", "max_tokens": 4,
                                     "stream": True})
        assert resp.headers["Content-Type"] == "text/event-stream"
        raw = [line[len(b"data: "):] for line in resp.read().split(b"\n\n")
               if line.startswith(b"data: ")]
        assert raw[-1] == b"[DONE]"
        chunks = [json.loads(x) for x in raw[:-1]]
        text = "".join(c["choices"][0]["text"] for c in chunks)
        assert text == out["choices"][0]["text"]
        assert chunks[-1]["choices"][0]["finish_reason"] == "length"

        # errors follow the OpenAI error envelope
        import urllib.error
        with pytest.raises(urllib.error.HTTPError) as e:
            post("/completions", {"model": "ghost", "prompt": "x"})
        assert e.value.code == 404
        assert "invalid_request_error" in e.value.read().decode()
    finally:
        srv.stop()
        eng.stop()


def test_embed_quant_per_row_scales_isolate_outlier_rows():
    """ADVICE r3: the embedding table quantizes with PER-ROW scales — one
    outlier row must not degrade every other token's embedding (per-column
    scales shared across the vocab would)."""
    rng = np.random.default_rng(0)
    V, D = 64, 32
    table = rng.standard_normal((V, D)).astype(np.float32)
    table[7] *= 1000.0  # single outlier row
    q = M.quantize_weights_int8({"embed": table})["embed"]
    assert q["s"].shape == (V, 1)
    rows = M._embed_rows({"q": jnp.asarray(q["q"]), "s": jnp.asarray(q["s"])},
                         jnp.asarray([3, 7]))
    err_normal = float(np.abs(np.asarray(rows[0], np.float32) - table[3]).max())
    err_outlier = float(np.abs(np.asarray(rows[1], np.float32) - table[7]).max())
    # per-row: each row keeps int8 precision relative to ITS OWN max; a
    # vocab-shared scale from the outlier would put err_normal near 4.0
    assert err_normal < 0.05, err_normal
    assert err_outlier < 50.0, err_outlier


def test_validation_markers_void_on_kernel_source_change(tmp_path, monkeypatch):
    """Chip-validation markers carry a sha of the kernel source they vouch
    for; an edited kernel voids the marker instead of riding a stale pass
    (code-review r4)."""
    import hashlib
    import os

    import bench
    from kubeflow_tpu.serving.engine import engine as E

    # flash marker: right sha -> promoted, wrong sha / no marker -> not
    marker = tmp_path / "FLASH_CHIP_VALIDATED"
    monkeypatch.setattr(bench, "_FLASH_VALIDATED", str(marker))
    assert not bench._flash_validated()
    src = os.path.join(os.path.dirname(E.__file__), "..", "..", "ops",
                       "flash_attention.py")
    good = hashlib.sha256(open(src, "rb").read()).hexdigest()
    marker.write_text(json.dumps({"kernel_sha": good}))
    assert bench._flash_validated()
    marker.write_text(json.dumps({"kernel_sha": "stale"}))
    assert not bench._flash_validated()

    # paged marker: wrong sha -> default stays off even with marker present
    pmarker = tmp_path / "PAGED_CHIP_VALIDATED"
    monkeypatch.setattr(E, "_PAGED_VALIDATED_MARKER", str(pmarker))
    monkeypatch.delenv("ENGINE_PAGED_KERNEL", raising=False)
    pmarker.write_text(json.dumps({"kernel_sha": "stale"}))
    assert E._paged_kernel_default() is False
    monkeypatch.setenv("ENGINE_PAGED_KERNEL", "1")
    assert E._paged_kernel_default() is True  # env override beats marker


def test_kernel_validate_flash_marker_survives_paged_failure(tmp_path, monkeypatch, capsys):
    """r4 chip session: the four flash stages passed on TPU but the paged
    stage (a DIFFERENT kernel with its own marker) raised — the harness must
    still write FLASH_CHIP_VALIDATED, keep the paged failure's real error
    text (not JAX's traceback-filtering notice), and exit non-zero so the
    chip queue retries instead of marking the job done."""
    import importlib
    import sys as _sys

    import bench

    kv = importlib.import_module("benchmarks.kernel_validate")
    stage_json = {
        s: json.dumps({"ok": True, "stage": s, "platform": "tpu"})
        for s in ("trivial", "flash1", "flash_bert", "flash_mask")
    }

    def fake_run(cmd, timeout_s, env):
        stage = cmd[-1]
        if stage == "paged":
            return 1, "", ("Traceback (most recent call last):\n"
                           "  ...\njax pallas internals\n"
                           "--------------------\n"
                           "For simplicity, JAX has removed its internal "
                           "frames from the traceback of the following "
                           "exception. Set JAX_TRACEBACK_FILTERING=off to "
                           "include these.\n"
                           "ValueError: mosaic layout failure\n")
        return 0, stage_json[stage] + "\n", ""

    monkeypatch.setattr(bench, "_run", fake_run)
    marker = tmp_path / "FLASH_CHIP_VALIDATED"
    monkeypatch.setattr(kv, "FLASH_MARKER", str(marker))
    monkeypatch.setattr(_sys, "argv", ["kernel_validate.py", "--all"])
    with pytest.raises(SystemExit) as exc:
        kv.main()
    assert exc.value.code == 1
    assert marker.exists()
    rec = json.loads(marker.read_text())
    assert all(s.get("stage") != "paged" for s in rec["stages"])
    out = capsys.readouterr().out
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["flash_ok"] and not summary["all_ok"]
    paged = next(s for s in summary["stages"] if s.get("stage") == "paged")
    assert "mosaic layout failure" in paged["error"]
    assert "For simplicity" not in paged["error"]


def test_reserve_page_composes_with_commit_and_release():
    """eng_reserve_page (speculative boundary drafting, VERDICT r3 weak #6):
    a reserved page means the commit that crosses into it allocates nothing;
    the per-slot cap and pool exhaustion return -1/-2; release frees it."""
    b = NativeBatcher(max_slots=2, num_pages=9, page_size=4, max_pages_per_slot=3)
    assert b.submit(1, 4, 8)           # exactly one page of prompt
    slot, *_ = b.admit()
    free0 = b.free_pages
    p = b.reserve_page(slot)
    assert p >= 1 and b.free_pages == free0 - 1
    # commits 5..8 fill the reserved page: no allocation reported
    for _ in range(4):
        rc, new_page = b.commit_token_ex(slot, False)
        assert rc == 1 and new_page == -1
    # commit 9 crosses into a third page: allocated normally
    rc, new_page = b.commit_token_ex(slot, False)
    assert rc == 1 and new_page >= 1
    # per-slot cap (3 pages owned): further reservation refused
    assert b.reserve_page(slot) == -1
    assert b.reserve_page(99) == -1    # bad slot
    b.release(slot)
    assert b.free_pages == free0 + 1   # prompt page + reserved + grown freed


def test_speculative_drafts_cross_page_boundaries(params):
    """Page-ahead reservation (VERDICT r3 weak #6): at a page boundary the
    drafter reserves the next page and proposes a full draft instead of
    clamping to zero — exercised against the REAL batcher, no jit."""
    from concurrent.futures import Future

    from kubeflow_tpu.serving.engine.engine import _Pending

    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=16, page_size=4, max_pages_per_slot=8,
        speculative="prompt_lookup", spec_max_draft=4,
    ))
    ctx = [3, 4, 5, 3, 4, 5, 3, 4]          # len 8 = exactly 2 pages
    assert eng.batcher.submit(7, len(ctx), 20)
    slot, rid, *_ = eng.batcher.admit()
    eng._slot_req[slot] = rid
    pending = _Pending(tokens=list(ctx), max_new_tokens=20, future=Future())
    pending.context = list(ctx)
    eng._requests[rid] = pending
    eng._pt_host[slot, :2] = eng.batcher.slot_pages(slot)[:2]
    eng._len_host[slot] = len(ctx)

    draft = eng._draft_for(slot, len(ctx))
    # final 2-gram (3,4) last occurred at 3 -> continuation [5,3,4]
    assert draft == [5, 3, 4], draft
    # the boundary was crossed by reserving the next page, mirrored locally
    assert int(np.count_nonzero(eng._pt_host[slot])) == 3
    # the reservation composes with commit: 4 commits fill it silently
    for _ in range(4):
        rc, new_page = eng.batcher.commit_token_ex(slot, False)
        assert rc == 1 and new_page == -1
    rc, new_page = eng.batcher.commit_token_ex(slot, False)
    assert rc == 1 and new_page >= 1       # next page allocated normally
    eng.batcher.release(slot)
    eng.stop()


def test_engine_stops_at_eos(params):
    """eos_id must end a generation early: pick the token greedy actually
    emits at step 2 as the eos, and the run must stop right there instead
    of generating to max_tokens."""
    prompt = [5, 7, 9, 11]
    oracle = greedy_oracle(params, prompt, 5)
    eng = Engine(params, CFG, EngineConfig(max_slots=1, num_pages=32,
                                           page_size=8, max_pages_per_slot=8,
                                           eos_id=oracle[1]))
    eng.start()
    try:
        out = eng.generate(prompt, 5)
        assert out["tokens"] == oracle[:2]  # eos token included, then stop
        assert out["num_tokens"] == 2 < 5
    finally:
        eng.stop()


def test_jetstream_reads_checkout_eos(tmp_path):
    """A real checkout's generation_config.json declares the stop token;
    the runtime must apply it unless engine.json explicitly set one."""
    from kubeflow_tpu.serving.engine.serve import JetStreamModel

    md = tmp_path / "m"
    md.mkdir()
    (md / "config.json").write_text(json.dumps(
        {"vocab_size": 101, "d_model": 64, "n_layers": 2, "n_heads": 4,
         "n_kv_heads": 2, "d_ff": 128}))
    (md / "engine.json").write_text(json.dumps(
        {"max_slots": 1, "num_pages": 32, "page_size": 8,
         "max_pages_per_slot": 8}))
    (md / "generation_config.json").write_text(json.dumps(
        {"eos_token_id": 2, "bos_token_id": 1}))
    m = JetStreamModel("llm", model_dir=str(md))
    m.load()
    try:
        assert m.engine.ec.eos_id == 2
    finally:
        m.engine.stop()

    # engine.json's explicit eos wins over the checkout's — INCLUDING an
    # explicit -1 ("never stop early", e.g. fixed-length benchmarking)
    for explicit in (7, -1):
        (md / "engine.json").write_text(json.dumps(
            {"max_slots": 1, "num_pages": 32, "page_size": 8,
             "max_pages_per_slot": 8, "eos_id": explicit}))
        m2 = JetStreamModel(f"llm{explicit}", model_dir=str(md))
        m2.load()
        try:
            assert m2.engine.ec.eos_id == explicit
        finally:
            m2.engine.stop()


def test_engine_stops_on_any_declared_eos(params):
    """ADVICE r4 (multi-EOS): Llama-3-Instruct declares [128001, 128009]
    and chat turns end with the SECOND id — the engine must stop on any
    member of the stop set, not just eos_id."""
    prompt = [5, 7, 9, 11]
    oracle = greedy_oracle(params, prompt, 5)
    eng = Engine(params, CFG, EngineConfig(max_slots=1, num_pages=32,
                                           page_size=8, max_pages_per_slot=8,
                                           eos_id=100,  # never emitted
                                           eos_ids=(99, oracle[1])))
    eng.start()
    try:
        out = eng.generate(prompt, 5)
        assert out["tokens"] == oracle[:2]
        assert out["num_tokens"] == 2 < 5
    finally:
        eng.stop()


def test_jetstream_reads_multi_eos_list(tmp_path):
    """A generation_config.json list keeps ALL stop ids (first as eos_id,
    rest as eos_ids), instead of collapsing to the first."""
    from kubeflow_tpu.serving.engine.serve import JetStreamModel

    md = tmp_path / "m"
    md.mkdir()
    (md / "config.json").write_text(json.dumps(
        {"vocab_size": 101, "d_model": 64, "n_layers": 2, "n_heads": 4,
         "n_kv_heads": 2, "d_ff": 128}))
    (md / "engine.json").write_text(json.dumps(
        {"max_slots": 1, "num_pages": 32, "page_size": 8,
         "max_pages_per_slot": 8}))
    (md / "generation_config.json").write_text(json.dumps(
        {"eos_token_id": [2, 9], "bos_token_id": 1}))
    m = JetStreamModel("llm", model_dir=str(md))
    m.load()
    try:
        assert m.engine.ec.eos_id == 2
        assert m.engine.ec.eos_ids == (9,)
        assert m.engine._stop_ids == {2, 9}
    finally:
        m.engine.stop()
