"""Overload-control tests (README "Overload control", serving/overload.py).

Coverage per the ISSUE 14 satellite list:

  * token-bucket quota accounting with explicit clocks (exact refill
    math, burst caps, tokens_left surfaces);
  * weighted fair admission under contention (2:1 weights -> ~2:1
    admitted) and the work-conserving lone-tenant case;
  * AIMD limit convergence with explicit clocks — multiplicative
    decrease under a burn signal down to the floor, additive increase
    while the limit is binding;
  * shed-lowest-SLO-class-first ordering at the concurrency limit;
  * deadline-aware early rejection: fires only below the observed p50
    queue+TTFT, NEVER on satisfiable requests or thin samples;
  * brownout enter/exit hysteresis (sustained pressure to enter, half
    the threshold sustained to exit) + incident events;
  * the 429 surface end to end: Retry-After header + machine-readable
    reason body through the real proxy, and the engine's own 503
    Retry-After;
  * engine honors ``parameters.brownout`` (speculation drafting off,
    brownout counter);
  * storm e2e through the real proxy: a seeded StormFaultConfig flood
    where every response is 200-or-429, ZERO admitted requests die of
    engine-queue deadline expiry, shedding happens, and the storm reads
    as ONE self-resolving capacity incident;
  * metrics exposition (ingress_shed_total / ingress_tenant_tokens /
    ingress_brownout_stage / engine_brownout_requests_total).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from kubeflow_tpu.serving import overload as O
from kubeflow_tpu.serving.engine import Engine, EngineConfig
from kubeflow_tpu.serving.engine import model as M
from kubeflow_tpu.serving.engine.faults import StormFaultConfig, storm_schedule
from kubeflow_tpu.serving.slo import RollingLatency

pytestmark = pytest.mark.overload

CFG = M.DecoderConfig(vocab_size=101, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=128)


@pytest.fixture(scope="module")
def params():
    return M.init(jax.random.PRNGKey(0), CFG)


# ----------------------------------------------------------- config parsing


def test_priority_classes_mirror_scheduler():
    """overload.py keeps its OWN copy of the class list so the router's
    import chain stays numpy/engine-free (pod cold-start budget — the
    scale-from-zero activation grace is 1.5s); this pin is what keeps
    the copy from drifting."""
    from kubeflow_tpu.serving.engine import scheduler

    assert O.PRIORITY_CLASSES == scheduler.PRIORITY_CLASSES
    assert O.PRIORITY_RANK == scheduler.PRIORITY_RANK


def test_config_from_json_validation():
    cfg = O.OverloadConfig.from_json(
        {"rate": 100, "limit": 8, "weights": {"a": 2, "b": 1},
         "class_headroom": {"interactive": 1.0, "batch": 0.8},
         "brownout_enter": [1.0, 2.0, 4.0]})
    assert cfg.rate == 100 and cfg.limit == 8
    assert dict(cfg.weights) == {"a": 2.0, "b": 1.0}
    with pytest.raises(ValueError, match="unknown overload config keys"):
        O.OverloadConfig.from_json({"ratee": 100})
    with pytest.raises(ValueError, match="md_factor"):
        O.OverloadConfig(md_factor=1.5)
    with pytest.raises(ValueError, match="brownout_enter"):
        O.OverloadConfig(brownout_enter=(2.0, 1.0, 4.0))
    with pytest.raises(ValueError, match="class_headroom"):
        O.OverloadConfig(class_headroom=(("gold", 1.0),))


# --------------------------------------------------------- quota accounting


def test_quota_accounting_explicit_clock():
    """Exact bucket math: cap = share * burst_s, drain by cost, refill
    at the share rate, shed with a load-derived Retry-After when dry."""
    c = O.OverloadController(O.OverloadConfig(rate=10.0, burst_s=2.0),
                             now=0.0)
    # lone tenant: share = full rate 10/s -> cap 20
    levels = []
    for _ in range(4):
        d = c.admit("t", "interactive", cost=5.0, deadline_s=None, now=0.0)
        assert d.admitted
        levels.append(d.tokens_left)
        c.release(d, ok=True, ttfb_s=None, now=0.0)
    assert levels == [15.0, 10.0, 5.0, 0.0]
    d = c.admit("t", "interactive", cost=5.0, deadline_s=None, now=0.0)
    assert not d.admitted and d.reason == "quota"
    assert d.retry_after_s > 0  # bucket refills 5 tokens in 0.5s
    assert d.tokens_left == 0.0
    # one second later the bucket holds 10 tokens again
    d = c.admit("t", "interactive", cost=5.0, deadline_s=None, now=1.0)
    assert d.admitted and d.tokens_left == 5.0


def test_weighted_fairness_under_contention():
    """Tenants at 2:1 weights, both over-driving their shares -> the
    admitted counts settle ~2:1; a request stream from ONE tenant later
    is work-conserving (gets the whole rate)."""
    c = O.OverloadController(O.OverloadConfig(
        rate=30.0, burst_s=0.1, weights=(("a", 2.0), ("b", 1.0))),
        now=0.0)
    admitted = {"a": 0, "b": 0}
    t = 0.0
    while t < 10.0:  # each tenant offers ~100/s against shares 20/10
        for tenant in ("a", "b"):
            d = c.admit(tenant, "interactive", cost=1.0, deadline_s=None,
                        now=t)
            if d.admitted:
                admitted[tenant] += 1
                c.release(d, ok=True, ttfb_s=None, now=t)
        t += 0.01
    ratio = admitted["a"] / max(1, admitted["b"])
    assert 1.6 < ratio < 2.4, admitted
    # both roughly at their fair share of the global rate over 10s
    assert 150 < admitted["a"] < 250, admitted
    # lone-tenant epoch: b goes quiet past the active window; a's share
    # becomes the whole rate (~30/s)
    base_a = admitted["a"]
    while t < 26.0:
        d = c.admit("a", "interactive", cost=1.0, deadline_s=None, now=t)
        if d.admitted:
            admitted["a"] += 1
            c.release(d, ok=True, ttfb_s=None, now=t)
        t += 0.01
    lone_rate = (admitted["a"] - base_a) / 16.0
    assert lone_rate > 24.0, lone_rate  # ~30/s, not the contended 20/s


# ------------------------------------------------------------ AIMD limiter


def test_aimd_limit_convergence_explicit_clock():
    cfg = O.OverloadConfig(limit=16, min_limit=2, adjust_interval_s=0.1,
                           burn_high=2.0, brownout=False)
    c = O.OverloadController(cfg, now=0.0)
    c.note_burn(9000, burn=5.0, now=0.0)  # worst replica burning hard
    seen = [c.limit]
    for i in range(1, 9):
        d = c.admit("t", "interactive", 1.0, None, now=0.2 * i)
        if d.admitted:
            c.release(d, ok=True, ttfb_s=None, now=0.2 * i)
        seen.append(c.limit)
    # multiplicative decrease: 16 -> 11.2 -> 7.84 -> ... -> floor 2
    assert seen[1] == pytest.approx(16 * 0.7)
    assert seen[2] == pytest.approx(16 * 0.49)
    assert all(b <= a for a, b in zip(seen, seen[1:]))
    assert c.limit == pytest.approx(2.0)
    # burn ages out (TTL 5s); a BINDING limit grows additively, step 1
    t = 20.0
    held = []
    for i in range(4):
        d = c.admit("t", "interactive", 1.0, None, now=t + 0.2 * i)
        if d.admitted:
            held.append(d)  # keep inflight high: the limit is binding
    grown = c.limit
    assert grown > 2.0 and grown <= 2.0 + 4.0  # additive, not a jump
    for d in held:
        c.release(d, ok=True, ttfb_s=None, now=t + 1.0)


def test_shed_lowest_class_first():
    """At the limit, best_effort gives way first, then batch, then (and
    only at the full limit) interactive — the lowest-SLO-class-first
    ordering."""
    cfg = O.OverloadConfig(limit=10, brownout=False)
    c = O.OverloadController(cfg, now=0.0)
    held = [c.admit("t", "interactive", 1.0, None, now=0.0)
            for _ in range(8)]
    assert all(d.admitted for d in held)  # inflight 8 of limit 10
    d = c.admit("t", "best_effort", 1.0, None, now=0.0)
    assert not d.admitted and d.reason == "concurrency"  # 8 >= 7.5
    d_b1 = c.admit("t", "batch", 1.0, None, now=0.0)
    assert d_b1.admitted                                  # 8 < 9
    d = c.admit("t", "batch", 1.0, None, now=0.0)
    assert not d.admitted                                 # 9 >= 9
    d_i1 = c.admit("t", "interactive", 1.0, None, now=0.0)
    assert d_i1.admitted                                  # 9 < 10
    d = c.admit("t", "interactive", 1.0, None, now=0.0)
    assert not d.admitted and d.retry_after_s > 0         # 10 >= 10
    by = c.snapshot(now=0.0)["shed_by"]
    assert by == {"batch:concurrency": 1, "best_effort:concurrency": 1,
                  "interactive:concurrency": 1}


def test_quota_debt_admits_oversized_requests():
    """A request costing more than the bucket CAP admits into debt (paid
    back at the share rate) — without it, a mixed-size tenant's large
    prompts livelock behind its own small traffic, shed with a
    Retry-After that interleaved small requests keep making a lie."""
    c = O.OverloadController(O.OverloadConfig(rate=10.0, burst_s=2.0),
                             now=0.0)
    d = c.admit("t", "interactive", cost=100.0, deadline_s=None, now=0.0)
    assert d.admitted and d.tokens_left == -80.0  # cap 20 -> debt
    c.release(d, ok=True, ttfb_s=None, now=0.0)
    # the debt throttles everything until paid back at 10/s
    d = c.admit("t", "interactive", cost=5.0, deadline_s=None, now=0.0)
    assert not d.admitted and d.reason == "quota"
    d = c.admit("t", "interactive", cost=5.0, deadline_s=None, now=9.0)
    assert d.admitted  # -80 + 90 refill -> cap-clamped 20, covers 5
    c.release(d, ok=True, ttfb_s=None, now=9.0)
    # an over-cap request needs a FULL bucket (not an accumulation the
    # cap would clamp anyway): once refilled to cap, it admits — small
    # interleaved traffic only delays it by its own cost, never forever
    d = c.admit("t", "interactive", 100.0, None, now=9.0)
    assert not d.admitted  # bucket at 5 of cap 20 after the small admit
    d = c.admit("t", "interactive", 100.0, None, now=11.0)
    assert d.admitted and d.tokens_left == -80.0


def test_overload_cost_charges_v1_batches_per_instance():
    """One V1 predict carrying N instances must cost ~N generates — a
    flat charge would make batching a quota/limiter bypass."""
    from kubeflow_tpu.serving.router import ServiceProxy

    one = ServiceProxy._overload_cost(
        {"text_input": "a" * 40, "parameters": {"max_tokens": 16}})
    batch = ServiceProxy._overload_cost(
        {"instances": [{"prompt": "a" * 40, "max_tokens": 16}] * 10})
    assert batch == pytest.approx(10 * one)
    assert ServiceProxy._overload_cost(
        {"instances": ["plain", "strings"]}) > 32


# ------------------------------------------------------ deadline early-reject


def test_deadline_early_reject_only_on_unsatisfiable():
    c = O.OverloadController(O.OverloadConfig(deadline_min_samples=8,
                                              brownout=False), now=0.0)
    # thin samples: NEVER rejects, whatever the deadline
    for dl in (0.001, 100.0):
        d = c.admit("t", "interactive", 1.0, deadline_s=dl, now=0.0)
        assert d.admitted
        c.release(d, ok=True, ttfb_s=0.5, now=0.0)
    for i in range(10):  # observed queue+TTFT p50 settles ~0.5s
        c.observe_ttfb("interactive", 0.5, now=0.1 * i)
    # satisfiable: deadline comfortably above p50 -> admitted
    d = c.admit("t", "interactive", 1.0, deadline_s=5.0, now=1.0)
    assert d.admitted
    c.release(d, ok=True, ttfb_s=0.5, now=1.0)
    # unsatisfiable: the queue would eat the whole budget before the
    # first token — refuse BEFORE any prefill is spent
    d = c.admit("t", "interactive", 1.0, deadline_s=0.1, now=1.0)
    assert not d.admitted and d.reason == "deadline"
    assert "p50" in d.detail and d.retry_after_s > 0
    # other classes keep their own estimator: batch has no samples
    d = c.admit("t", "batch", 1.0, deadline_s=0.1, now=1.0)
    assert d.admitted


# ------------------------------------------------------- brownout hysteresis


def test_brownout_enter_exit_hysteresis():
    cfg = O.OverloadConfig(adjust_interval_s=0.1, brownout_hold_s=0.5,
                           burn_high=2.0, burn_ttl_s=5.0)
    c = O.OverloadController(cfg, now=0.0)

    def tick(now):
        d = c.admit("t", "interactive", 1.0, None, now=now)
        if d.admitted:
            c.release(d, ok=True, ttfb_s=None, now=now)
        return d

    c.note_burn(1, burn=3.0, now=0.0)  # pressure 1.5 >= enter[0]=1.0
    tick(0.2)
    assert c.stage == 0  # above threshold but not yet for hold_s
    tick(0.4)
    assert c.stage == 0
    tick(0.9)  # sustained > 0.5s since first-above (0.2)
    assert c.stage == 1
    # pressure 1.5 < enter[1]=2.0: never climbs to stage 2
    tick(1.4)
    assert c.stage == 1
    # exit needs pressure < enter[0] * 0.5 SUSTAINED; burn TTL expires
    # at t=5 so pressure collapses to 0
    tick(5.5)
    assert c.stage == 1  # below, but not yet for hold_s
    tick(6.2)
    assert c.stage == 0
    events = c.drain_events()
    stages = [(e["from_stage"], e["stage"]) for e in events
              if e["kind"] == "brownout"]
    assert stages == [(0, 1), (1, 0)]


def test_brownout_blip_does_not_enter():
    cfg = O.OverloadConfig(adjust_interval_s=0.1, brownout_hold_s=0.5,
                           burn_high=2.0, burn_ttl_s=0.3)
    c = O.OverloadController(cfg, now=0.0)
    c.note_burn(1, burn=10.0, now=0.0)  # a blip: TTL 0.3s

    def tick(now):
        d = c.admit("t", "interactive", 1.0, None, now=now)
        if d.admitted:
            c.release(d, ok=True, ttfb_s=None, now=now)

    tick(0.2)
    tick(0.6)   # burn already stale: pressure back to 0 before hold_s
    tick(1.2)
    assert c.stage == 0
    assert not [e for e in c.drain_events() if e["kind"] == "brownout"]


# ----------------------------------------------------- body rewrite (router)


def test_apply_brownout_body_rewrite():
    from kubeflow_tpu.serving.router import ServiceProxy

    cfg = O.OverloadConfig(brownout_max_tokens=8)
    body, p = ServiceProxy._apply_brownout(
        {"text_input": "hi", "parameters": {"max_tokens": 64}}, 1, cfg)
    assert p["parameters"]["max_tokens"] == 8
    assert "brownout" not in p["parameters"]  # stage 1: clamp only
    body, p = ServiceProxy._apply_brownout(
        {"text_input": "hi"}, 2, cfg)
    assert p["parameters"] == {"max_tokens": 8, "brownout": 2}
    assert json.loads(body) == p
    # OpenAI-shaped body: top-level max_tokens clamps and the engine
    # marker rides top-level too (server._openai forwards it into the
    # engine parameters — stage >= 2 must reach this surface as well)
    _, p = ServiceProxy._apply_brownout(
        {"prompt": "hi", "max_tokens": 100}, 3, cfg)
    assert p["max_tokens"] == 8
    assert p["brownout"] == 3
    _, p = ServiceProxy._apply_brownout(
        {"messages": [{"role": "user", "content": "hi"}]}, 1, cfg)
    assert "brownout" not in p  # stage 1: clamp only, no engine marker
    # V1 predict instances clamp per instance
    _, p = ServiceProxy._apply_brownout(
        {"instances": [{"prompt": "a", "max_tokens": 50}, "plain"]}, 1, cfg)
    assert p["instances"][0]["max_tokens"] == 8


# ------------------------------------------------- engine honors brownout


def test_engine_brownout_disables_spec_drafting(params):
    """``parameters.brownout: 2`` turns speculation drafting off for that
    request (same bytes, no verify dispatches) and counts the stage in
    engine_brownout_requests_total."""
    from kubeflow_tpu.serving.engine.serve import JetStreamModel

    eng = Engine(params, CFG, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=12,
        speculative="prompt_lookup", spec_ngram=1, spec_max_draft=4))
    model = JetStreamModel("m", "", engine=eng)
    model.load()
    try:
        # a repetitive prompt so prompt-lookup actually drafts
        prompt = "abcabcabcabcabcabcabcabc"
        r0 = model.generate({"text_input": prompt,
                             "parameters": {"max_tokens": 12}})

        def drafted() -> float:
            for line in model.metrics_text().splitlines():
                if line.startswith("engine_spec_draft_tokens_total"):
                    return float(line.rsplit(" ", 1)[1])
            return 0.0

        base = drafted()
        assert base > 0  # sanity: the spec path IS live for this prompt
        r1 = model.generate({"text_input": prompt,
                             "parameters": {"max_tokens": 12,
                                            "brownout": 2}})
        assert drafted() == base  # no drafts proposed under brownout
        assert r1["token_ids"] == r0["token_ids"]  # quality, not bytes
        text = model.metrics_text()
        assert 'engine_brownout_requests_total{stage="2",model="m"} 1' \
            in text
        with pytest.raises(Exception, match="brownout"):
            model.generate({"text_input": "x",
                            "parameters": {"brownout": 9}})
        with pytest.raises(Exception, match="brownout"):
            # bool subclasses int: "brownout": true must 400, not run
            # silently at stage 1 with a stage="True" metric label
            model.generate({"text_input": "x",
                            "parameters": {"brownout": True}})
        # V1 predict carries the marker top-level for the whole batch
        out = model.predict({"instances": [{"prompt": "ab",
                                            "max_tokens": 4}],
                             "brownout": 2})
        assert out[0]["tokens"] > 0
        assert 'engine_brownout_requests_total{stage="2",model="m"} 2' \
            in model.metrics_text()
    finally:
        eng.stop(drain=False)


# -------------------------------------------------------------- HTTP surface


def _mk_fleet(overload_ann, params, n_rep=1, svc="ovl", ec_kw=None):
    """N engine replicas behind the real ServiceProxy with the overload
    annotation set.  Returns (api, proxy, svc_port, engines, servers)."""
    from kubeflow_tpu.core.api import APIServer
    from kubeflow_tpu.serving.api import LABEL_ISVC
    from kubeflow_tpu.serving.controllers import (POD_PORT_ANNOTATION,
                                                  PROXY_PORT_ANNOTATION)
    from kubeflow_tpu.serving.engine.serve import JetStreamModel
    from kubeflow_tpu.serving.router import (OVERLOAD_ANNOTATION,
                                             ServiceProxy)
    from kubeflow_tpu.serving.server import ModelServer
    from kubeflow_tpu.utils.net import find_free_ports

    api = APIServer()
    proxy = ServiceProxy(api)
    svc_port = find_free_ports(1)[0]
    ann = {PROXY_PORT_ANNOTATION: str(svc_port)}
    if overload_ann is not None:
        ann[OVERLOAD_ANNOTATION] = overload_ann
    api.create({
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": svc, "labels": {LABEL_ISVC: svc},
                     "annotations": ann},
        "spec": {"selector": {"app": svc}}})
    engines, servers = [], []
    base = dict(max_slots=4, num_pages=256, page_size=8,
                max_pages_per_slot=20)
    base.update(ec_kw or {})
    for i in range(n_rep):
        eng = Engine(params, CFG, EngineConfig(**base))
        srv = ModelServer([JetStreamModel(svc, "", engine=eng)], port=0)
        srv.start()
        api.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"{svc}-{i}", "labels": {"app": svc},
                         "annotations": {POD_PORT_ANNOTATION:
                                         str(srv.port)}},
            "spec": {},
            "status": {"phase": "Running",
                       "conditions": [{"type": "Ready",
                                       "status": "True"}]}})
        engines.append(eng)
        servers.append(srv)
    proxy.sync()
    return api, proxy, svc_port, engines, servers


def _teardown(proxy, engines, servers):
    proxy.shutdown()
    for srv in servers:
        srv.stop()
    for eng in engines:
        try:
            eng.stop(drain=False)
        except Exception:  # noqa: BLE001
            pass


def _post(port, svc, payload, headers=None, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v2/models/{svc}/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), json.loads(r.read())


def test_quota_429_retry_after_through_proxy(params):
    """The 429 surface end to end: a tenant over its quota gets
    Retry-After + a machine-readable reason body; another tenant's
    bucket is untouched (isolation)."""
    ann = json.dumps({"rate": 1.0, "burst_s": 1.0, "limit": 0,
                      "brownout": False})
    api, proxy, port, engines, servers = _mk_fleet(ann, params)
    try:
        payload = {"text_input": "hello world", "parameters":
                   {"max_tokens": 8}}
        st, hdrs, _ = _post(port, "ovl", payload,
                            headers={"X-Tenant-Id": "hog"})
        assert st == 200
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(port, "ovl", payload, headers={"X-Tenant-Id": "hog"})
        e = ei.value
        assert e.code == 429
        assert float(e.headers["Retry-After"]) > 0
        body = json.loads(e.read())
        assert body["reason"] == "quota"
        assert body["tenant"] == "hog"
        assert body["class"] == "interactive"
        # the OTHER tenant still admits: per-tenant isolation
        st, _, _ = _post(port, "ovl", payload,
                         headers={"X-Tenant-Id": "quiet"})
        assert st == 200
        from kubeflow_tpu.core.metrics import REGISTRY

        text = REGISTRY.render()
        assert 'ingress_shed_total{' in text and 'reason="quota"' in text
        assert "ingress_tenant_tokens{" in text
        assert "ingress_brownout_stage{" in text
    finally:
        _teardown(proxy, engines, servers)


def test_engine_503_carries_retry_after(params, monkeypatch):
    """Engine-side admission refusals (EngineOverloaded) answer 503 with
    Retry-After and a machine-readable reason — same contract as the
    ingress 429s, one surface for clients either way."""
    from kubeflow_tpu.serving.engine.serve import JetStreamModel
    from kubeflow_tpu.serving.server import ModelServer

    # slow ticks so the flood actually stacks behind the 1-slot engine
    monkeypatch.setenv("ENGINE_TICK_FLOOR_S", "0.05")
    eng = Engine(params, CFG, EngineConfig(
        max_slots=1, num_pages=64, page_size=8, max_pages_per_slot=12,
        max_queue_depth=1))
    srv = ModelServer([JetStreamModel("m", "", engine=eng)], port=0)
    srv.start()
    try:
        # saturate: one slot + one queue seat, then flood
        seen = {"status": None, "headers": None, "body": None}
        barrier = threading.Barrier(8)
        threads = []

        def fire():
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v2/models/m/generate",
                data=json.dumps({"text_input": "x" * 64,
                                 "parameters": {"max_tokens": 16}}).encode(),
                headers={"Content-Type": "application/json"})
            barrier.wait(timeout=30)
            try:
                with urllib.request.urlopen(req, timeout=120) as r:
                    r.read()
            except urllib.error.HTTPError as e:
                if e.code == 503 and seen["status"] is None:
                    seen["status"] = 503
                    seen["headers"] = dict(e.headers)
                    seen["body"] = json.loads(e.read())

        for _ in range(8):
            t = threading.Thread(target=fire)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=120)
        assert seen["status"] == 503, "no request hit the queue bound"
        assert float(seen["headers"]["Retry-After"]) > 0
        assert seen["body"]["reason"] == "engine_overloaded"
        assert seen["body"]["retry_after_s"] > 0
    finally:
        srv.stop()
        eng.stop(drain=False)


def test_storm_e2e_admitted_never_die_in_queue(params):
    """The acceptance storm: a seeded StormFaultConfig flood through the
    real proxy with the controller on.  Every response is 200 or
    429+Retry-After — no hangs, no 504 engine-queue deadline expiries
    for admitted requests — shedding actually happens, and the whole
    storm lands as ONE self-resolving capacity incident."""
    ann = json.dumps({"limit": 4, "min_limit": 2, "rate": 0,
                      "adjust_interval_s": 0.2,
                      "brownout": False})
    api, proxy, port, engines, servers = _mk_fleet(ann, params)
    try:
        storm = storm_schedule(StormFaultConfig(
            seed=7, duration_s=1.5, base_qps=40.0, burst_every_s=0.75,
            burst_len_s=0.25, burst_x=3.0, tenants=3,
            prompt_len_median=32, prompt_len_max=128, max_tokens=8))
        assert len(storm) > 40
        results = []
        lock = threading.Lock()

        def fire(arr):
            payload = {"text_input": "a" * arr.prompt_len,
                       "parameters": {"max_tokens": arr.max_tokens,
                                      "priority": arr.priority,
                                      "deadline_s": 60.0}}
            try:
                st, hdrs, body = _post(port, "ovl", payload,
                                       headers={"X-Tenant-Id": arr.tenant},
                                       timeout=120)
                rec = (st, hdrs, body)
            except urllib.error.HTTPError as e:
                rec = (e.code, dict(e.headers), json.loads(e.read()))
            with lock:
                results.append(rec)

        t0 = time.monotonic()
        threads = []
        for arr in storm:
            delay = t0 + arr.t_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=fire, args=(arr,))
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=180)
        assert len(results) == len(storm)  # no hangs: every request answered
        codes = sorted({st for st, _, _ in results})
        assert set(codes) <= {200, 429}, codes  # zero 504s / 5xxs
        shed = [(h, b) for st, h, b in results if st == 429]
        assert shed, "storm never shed — the limiter did nothing"
        for hdrs, body in shed:
            assert float(hdrs["Retry-After"]) > 0
            assert body["reason"] in ("quota", "concurrency", "deadline")
        ok = sum(1 for st, _, _ in results if st == 200)
        assert ok > 0
        # ONE classified capacity incident, not an alert storm
        state = next(iter(proxy._states.values()))
        deadline = time.monotonic() + 10.0
        incs = []
        while time.monotonic() < deadline:
            incs = [i for i in state.incidents.list()
                    if i["cause"] == "capacity"]
            if incs:
                break
            time.sleep(0.1)
        assert len(incs) == 1, incs
        assert incs[0]["detector"] == "admission_pressure"
        ev = incs[0]["evidence"].get("overload") or {}
        assert ev.get("shed_total", 0) > 0  # the bundle cites shed counts
        assert "stage" in ev
        # the acceptance gate: ZERO admitted requests died in an engine
        # queue (no deadline sheds, no engine-side rejections leaked)
        for e in engines:
            s = e.stats
            assert s["requests_shed"] == 0, s
            assert s["requests_rejected"] == 0, s
    finally:
        _teardown(proxy, engines, servers)


# --------------------------------------------------------- RollingLatency


def test_rolling_latency_window_math():
    rl = RollingLatency(window_s=10.0)
    for i in range(10):
        rl.observe(0.1 * (i + 1), now=float(i))
    assert rl.count(now=9.0) == 10
    assert rl.quantile(0.5, now=9.0) == pytest.approx(0.6)
    assert rl.minimum(now=9.0) == pytest.approx(0.1)
    # stale samples age out of the window
    rl.observe(5.0, now=30.0)
    assert rl.count(now=30.0) == 1
    assert rl.minimum(now=30.0) == pytest.approx(5.0)
    assert RollingLatency().quantile(0.5, now=0.0) is None
