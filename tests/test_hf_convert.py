"""HF checkpoint → engine conversion: numeric parity with transformers.

The migration story for the reference's huggingfaceserver users: point an
InferenceService at an HF Llama checkout and the JetStream runtime serves
it.  These tests pin the weight mapping against transformers' own forward
pass — the one oracle that can catch a transposed projection or a wrong
RoPE convention.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
transformers = pytest.importorskip("transformers")


def _tiny_hf_llama(tmp_path, tie=False):
    import torch

    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=tie,
        attention_bias=False, mlp_bias=False)
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg).eval()
    src = tmp_path / ("hf_tied" if tie else "hf")
    model.save_pretrained(src)  # safetensors by default
    return model, str(src)


@pytest.mark.slow  # fast lane must stay under its 5-min budget (r1 #10)
@pytest.mark.parametrize("tie", [False, True])
def test_converted_logits_match_transformers(tmp_path, tie):
    import torch

    from kubeflow_tpu.serving.engine import model as M
    from kubeflow_tpu.serving.engine.hf_convert import convert_hf_checkpoint

    hf, src = _tiny_hf_llama(tmp_path, tie=tie)
    out = tmp_path / "engine"
    cfg_dict = convert_hf_checkpoint(src, str(out), dtype="float32")
    assert cfg_dict["n_kv_heads"] == 2 and cfg_dict["d_model"] == 64

    config = M.DecoderConfig.from_dir(str(out))
    params = {k: jnp.asarray(v, jnp.float32)
              for k, v in np.load(out / "params.npz").items()}

    toks = np.array([[5, 17, 99, 3, 42, 7]], np.int64)
    with __import__("torch").no_grad():
        ref = hf(torch.from_numpy(toks)).logits.numpy()  # [1, S, V]
    got = np.asarray(M.forward_full(params, config, jnp.asarray(toks, jnp.int32)))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


@pytest.mark.slow  # compile + transformers forward; llama parity covers fast
def test_gemma_converted_logits_match_transformers(tmp_path):
    """Gemma-1's block deltas (GeGLU, +1 norms folded at conversion,
    sqrt(d_model) input scaling, decoupled head_dim, tied embeddings) must
    reproduce transformers' forward — the oracle that catches a missed
    delta, which would serve silently-wrong real Gemma checkpoints."""
    import torch

    from kubeflow_tpu.serving.engine import model as M
    from kubeflow_tpu.serving.engine.hf_convert import convert_hf_checkpoint

    cfg = transformers.GemmaConfig(
        vocab_size=128, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16,  # decoupled: 48/4 = 12 != 16
        rope_theta=10000.0, rms_norm_eps=1e-6)
    torch.manual_seed(0)
    hf = transformers.GemmaForCausalLM(cfg).eval()
    src = tmp_path / "gemma"
    hf.save_pretrained(src)

    out = tmp_path / "engine"
    cfg_dict = convert_hf_checkpoint(str(src), str(out), dtype="float32")
    assert cfg_dict["head_dim_override"] == 16
    assert cfg_dict["act"] == "gelu_tanh" and cfg_dict["scale_embed"] is True

    config = M.DecoderConfig.from_dir(str(out))
    assert config.head_dim == 16
    params = {k: jnp.asarray(v, jnp.float32)
              for k, v in np.load(out / "params.npz").items()}

    toks = np.array([[5, 17, 99, 3, 42, 7]], np.int64)
    with torch.no_grad():
        ref = hf(torch.from_numpy(toks)).logits.numpy()
    got = np.asarray(M.forward_full(params, config,
                                    jnp.asarray(toks, jnp.int32)))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_rejects_non_llama_architectures(tmp_path):
    from kubeflow_tpu.serving.engine.hf_convert import convert_hf_checkpoint

    d = tmp_path / "gemma2"
    d.mkdir()
    (d / "config.json").write_text(json.dumps(
        {"model_type": "gemma2", "vocab_size": 10, "hidden_size": 8}))
    with pytest.raises(ValueError, match="gemma2"):
        convert_hf_checkpoint(str(d), str(tmp_path / "out"))


def test_rope_scaling_rejected_but_decoupled_head_dim_maps(tmp_path):
    """Llama-3.1+ rope_scaling changes math the engine doesn't implement —
    it must raise.  Mistral-Nemo-style explicit head_dim IS expressible
    (head_dim_override) and maps instead of rejecting."""
    from kubeflow_tpu.serving.engine.hf_convert import (_map_config,
                                                        convert_hf_checkpoint)

    base = {"model_type": "llama", "vocab_size": 64, "hidden_size": 32,
            "num_hidden_layers": 1, "num_attention_heads": 4,
            "intermediate_size": 64}
    d1 = tmp_path / "scaled"
    d1.mkdir()
    (d1 / "config.json").write_text(json.dumps(
        dict(base, rope_scaling={"rope_type": "llama3", "factor": 8.0})))
    with pytest.raises(ValueError, match="rope_scaling"):
        convert_hf_checkpoint(str(d1), str(tmp_path / "o1"))

    mapped = _map_config(dict(base, head_dim=16))  # 32/4 = 8 != 16
    assert mapped["head_dim_override"] == 16


def test_from_dir_refuses_raw_hf_config(tmp_path):
    """A raw HF config silently filtered through DecoderConfig would serve
    with DEFAULT dims — it must raise instead."""
    from kubeflow_tpu.serving.engine.model import DecoderConfig

    (tmp_path / "config.json").write_text(json.dumps(
        {"model_type": "llama", "vocab_size": 128, "hidden_size": 64}))
    with pytest.raises(ValueError, match="HuggingFace"):
        DecoderConfig.from_dir(str(tmp_path))


def test_load_tokenizer_detects_hf_tokenizers_format(tmp_path):
    """A real checkout's tokenizer.json is the HF tokenizers-library format
    — ids must come from the checkpoint's own vocabulary, not from reading
    the file as a flat {token: id} dict."""
    from tokenizers import Tokenizer, models, pre_tokenizers

    from kubeflow_tpu.serving.engine.serve import (HFTokenizer,
                                                   VocabTokenizer,
                                                   load_tokenizer)

    tok = Tokenizer(models.WordLevel(
        {"hello": 7, "world": 3, "[UNK]": 0}, unk_token="[UNK]"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    tok.save(str(tmp_path / "tokenizer.json"))

    loaded = load_tokenizer(str(tmp_path))
    assert isinstance(loaded, HFTokenizer)
    assert loaded.encode("hello world") == [7, 3]
    assert loaded.decode([7, 3]).strip() == "hello world"

    flat = tmp_path / "flat"
    flat.mkdir()
    (flat / "tokenizer.json").write_text(json.dumps({"hi": 1, "yo": 2}))
    assert isinstance(load_tokenizer(str(flat)), VocabTokenizer)


@pytest.mark.slow
def test_hf_checkpoint_finetunes_then_serves(tmp_path):
    """The tune→deploy loop on a REAL checkpoint format: convert an HF
    checkout, fine-tune it with the trainable decoder family (shared
    init/forward with the serving engine), and check the tuned weights
    still drive the engine's forward — the reference's Gemma pipeline
    shape (BASELINE config[4]) with HF provenance."""
    import jax

    from kubeflow_tpu.models import decoder
    from kubeflow_tpu.parallel.mesh import MeshConfig, build_mesh
    from kubeflow_tpu.serving.engine import model as M
    from kubeflow_tpu.serving.engine.hf_convert import convert_hf_checkpoint
    from kubeflow_tpu.train.trainer import Trainer, TrainerConfig

    _, src = _tiny_hf_llama(tmp_path)
    out = tmp_path / "engine"
    convert_hf_checkpoint(src, str(out), dtype="float32")
    config = M.DecoderConfig.from_dir(str(out))
    params = {k: jnp.asarray(v, jnp.float32)
              for k, v in np.load(out / "params.npz").items()}

    mesh = build_mesh(MeshConfig(data=1, fsdp=1, tensor=1), jax.devices()[:1])

    def loss_fn(p, batch):
        return decoder.lm_loss(p, config, batch["tokens"])

    tr = Trainer(loss_fn, params, mesh, decoder.SHARDING_RULES,
                 TrainerConfig(learning_rate=5e-3, warmup_steps=1,
                               total_steps=8))
    data = decoder.synthetic_lm_batches(config.vocab_size, 4, 16)
    losses = [float(tr.train_step(next(data))["loss"]) for _ in range(6)]
    assert losses[-1] < losses[0], losses  # it trains

    toks = jnp.asarray(np.array([[5, 17, 9]], np.int32))
    logits = M.forward_full(tr.params, config, toks)  # tuned weights serve
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.slow
def test_isvc_serves_raw_hf_checkout_end_to_end(tmp_path):
    """Full platform path on an unconverted HF checkout: ISVC -> storage
    init -> JetStream runtime auto-converts -> generation completes."""
    import os

    from kubeflow_tpu.core.cluster import Cluster
    from kubeflow_tpu.serving import install
    from kubeflow_tpu.serving.api import inference_service

    _, src = _tiny_hf_llama(tmp_path)
    with open(os.path.join(src, "engine.json"), "w") as f:
        json.dump({"max_slots": 2, "num_pages": 32, "page_size": 8}, f)

    c = Cluster(cpu_nodes=1, tpu_slices=(("s0", "v5e", "2x2"),),
                base_env={"PYTHONPATH": os.getcwd(), "JAX_PLATFORMS": "cpu"})
    router, proxy = install(c.api, c.manager)
    try:
        c.apply(inference_service("hfllm", model_format="llama",
                                  storage_uri=f"file://{src}"))

        def ready():
            st = (c.api.try_get("InferenceService", "hfllm") or {}).get("status", {})
            return any(cond["type"] == "Ready" and cond["status"] == "True"
                       for cond in st.get("conditions", []))
        assert c.wait_for(ready, timeout=180), "ISVC on HF checkout never Ready"
        out = router.predict("hfllm", {"instances": [
            {"prompt": "hi", "max_tokens": 4}]})
        assert out["predictions"][0]["tokens"] == 4
    finally:
        proxy.shutdown()
        c.shutdown()


@pytest.mark.slow  # builds a transformers checkpoint
def test_truncated_checkpoint_names_the_missing_tensor(tmp_path):
    """ADVICE r4: a checkout whose config claims more layers than its
    shards contain must fail with the missing tensor's name, not a raw
    KeyError from the mapper."""
    from kubeflow_tpu.serving.engine.hf_convert import convert_hf_checkpoint

    _, src = _tiny_hf_llama(tmp_path)
    cfg = json.loads((tmp_path / "hf" / "config.json").read_text())
    cfg["num_hidden_layers"] = 3  # shards only hold layers 0-1
    (tmp_path / "hf" / "config.json").write_text(json.dumps(cfg))
    with pytest.raises(ValueError, match="missing tensor.*model.layers.2"):
        convert_hf_checkpoint(src, str(tmp_path / "out"), dtype="float32")
