"""Parity batch: CMA-ES, medianstop early stopping, controller metrics,
profiler/parallelism env surfacing."""

import sys
import urllib.request

import numpy as np
import pytest

from kubeflow_tpu.core.cluster import Cluster
from kubeflow_tpu.core import metrics as cmetrics
from kubeflow_tpu.katib import api as kapi
from kubeflow_tpu.katib.api import Parameter, experiment
from kubeflow_tpu.katib.client import KatibClient
from kubeflow_tpu.katib.controllers import install as katib_install
from kubeflow_tpu.katib.suggest import algorithm_names, get_suggester
from kubeflow_tpu.training.api import ReplicaSpec, job
from kubeflow_tpu.training.client import TrainingClient
from kubeflow_tpu.training.frameworks import install as training_install


@pytest.fixture()
def kcluster():
    c = Cluster(cpu_nodes=1)
    training_install(c.api, c.manager)
    katib_install(c.api, c.manager, c.logs)
    yield c
    c.shutdown()


# ------------------------------------------------------------------- cma-es


def _quadratic_experiment(n_trials_done: int):
    """Experiment + synthetic completed trials for f(x) = 1 - (x-0.3)^2."""
    exp = experiment(
        "cma",
        parameters=[Parameter("x", "double", min=0.0, max=1.0)],
        trial_spec={"kind": "TPUJob", "spec": {}},
        objective_metric="acc",
        objective_type="maximize",
        algorithm="cmaes",
        max_trials=50,
    )
    rng = np.random.default_rng(0)
    trials = []
    for i in range(n_trials_done):
        x = float(rng.uniform(0, 1))
        trials.append(
            {
                "metadata": {"name": f"t{i}"},
                "spec": {"parameterAssignments": [{"name": "x", "value": x}]},
                "status": {
                    "conditions": [{"type": kapi.SUCCEEDED, "status": "True"}],
                    "observation": {"metrics": [{"name": "acc", "latest": 1 - (x - 0.3) ** 2}]},
                },
            }
        )
    return exp, trials


def test_cmaes_registered_and_converges_toward_optimum():
    assert "cmaes" in algorithm_names()
    s = get_suggester("cmaes")
    exp, trials = _quadratic_experiment(0)
    first = s.suggest(exp, trials, 4)
    assert len(first) == 4 and all(0.0 <= a["x"] <= 1.0 for a in first)

    # after several generations of observations, the sampling mean should
    # have moved toward x*=0.3
    exp, trials = _quadratic_experiment(40)
    later = s.suggest(exp, trials, 16)
    mean_later = np.mean([a["x"] for a in later])
    assert abs(mean_later - 0.3) < 0.2, mean_later


# ----------------------------------------------------------- early stopping

SLOW_BAD_TRIAL = (
    "import os, time\n"
    "lr = float(os.environ['LR'])\n"
    "acc = 1.0 - (lr - 0.1) ** 2\n"
    "print(f'accuracy={acc:.6f}', flush=True)\n"
    # bad trials linger: early stopping must kill them before the sleep ends
    "time.sleep(0 if acc > 0.5 else 20)\n"
)


# slow lane: ~15s E2E; early-stop condition handling keeps fast coverage via the obslog store tests
@pytest.mark.slow
def test_medianstop_early_stops_bad_trials(kcluster):
    trial_spec = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "TPUJob",
        "spec": {
            "replicaSpecs": {"Worker": {
                "replicas": 1, "restartPolicy": "Never",
                "template": {"spec": {"containers": [{
                    "name": "main",
                    "command": [sys.executable, "-u", "-c", SLOW_BAD_TRIAL],
                    "env": [{"name": "LR", "value": "${trialParameters.lr}"}],
                }]}},
            }},
            "runPolicy": {"cleanPodPolicy": "None"},
        },
    }
    spec = experiment(
        "medstop",
        parameters=[Parameter("lr", "double", min=0.01, max=2.0)],
        trial_spec=trial_spec,
        objective_metric="accuracy",
        objective_type="maximize",
        algorithm="grid",
        max_trials=4,  # 2 baselines for the median + 2 early-stop candidates
        parallel_trials=2,
    )
    spec["spec"]["earlyStopping"] = {
        "algorithmName": "medianstop",
        "algorithmSettings": [{"name": "min_trials_required", "value": 2}],
    }
    client = KatibClient(kcluster)
    client.create_experiment(spec)
    assert client.wait_for_experiment("medstop", timeout=300) == kapi.SUCCEEDED
    trials = client.list_trials("medstop")
    stopped = [
        t for t in trials
        if any(c["type"] == kapi.EARLY_STOPPED and c["status"] == "True"
               for c in t.get("status", {}).get("conditions", []))
    ]
    assert stopped, "no trial was early-stopped"
    # early-stopped trials still carry their observation
    assert all(t["status"].get("observation", {}).get("metrics") for t in stopped)


# ----------------------------------------------------------------- metrics


def test_controller_metrics_counted_and_served(kcluster):
    client = TrainingClient(kcluster)
    base_created = cmetrics.JOBS_CREATED.value(kind="TPUJob")
    base_ok = cmetrics.JOBS_SUCCESSFUL.value(kind="TPUJob")
    spec = job("TPUJob", "mjob", {"Worker": ReplicaSpec(
        replicas=1, command=[sys.executable, "-c", "print('ok')"],
    )})
    client.create_job(spec)
    client.wait_for_job("TPUJob", "mjob", timeout=60)
    assert cmetrics.JOBS_CREATED.value(kind="TPUJob") == base_created + 1
    assert cmetrics.JOBS_SUCCESSFUL.value(kind="TPUJob") == base_ok + 1
    assert cmetrics.RECONCILE_TOTAL.value(controller="TPUJob", result="success") > 0

    port, server = cmetrics.serve(0)
    try:
        body = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "training_operator_jobs_successful_total" in body
        assert 'controller_runtime_reconcile_total{controller="TPUJob"' in body
    finally:
        server.shutdown()


def test_jobs_failed_metric(kcluster):
    client = TrainingClient(kcluster)
    base = cmetrics.JOBS_FAILED.value(kind="TPUJob")
    spec = job("TPUJob", "failjob", {"Worker": ReplicaSpec(
        replicas=1, command=[sys.executable, "-c", "raise SystemExit(1)"],
    )})
    client.create_job(spec)
    client.wait_for_job("TPUJob", "failjob", timeout=60)
    assert cmetrics.JOBS_FAILED.value(kind="TPUJob") == base + 1


# ----------------------------------------- profiler + parallelism env wiring


def test_tpujob_profile_and_preset_env(kcluster):
    spec = job("TPUJob", "profjob", {"Worker": ReplicaSpec(
        replicas=1,
        command=[sys.executable, "-u", "-c",
                 "import os; print('DIR', os.environ.get('TPU_PROFILE_DIR'));"
                 "print('STEPS', os.environ.get('TPU_PROFILE_STEPS'));"
                 "print('PRESET', os.environ.get('TPU_PARALLELISM_PRESET'))"],
    )})
    spec["spec"]["profile"] = {"enabled": True, "dir": "/tmp/prof", "steps": 3}
    spec["spec"]["parallelism"] = {"preset": "ring-cp"}
    client = TrainingClient(kcluster)
    client.create_job(spec)
    client.wait_for_job("TPUJob", "profjob", timeout=60)
    logs = "\n".join(client.get_job_logs("TPUJob", "profjob").values())
    assert "DIR /tmp/prof" in logs
    assert "STEPS 3" in logs
    assert "PRESET ring-cp" in logs


def test_maybe_trace_noop_without_env(tmp_path):
    from kubeflow_tpu.parallel.profiling import maybe_trace

    with maybe_trace(0, environ={}) as tracing:
        assert tracing is False
