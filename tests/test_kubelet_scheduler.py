"""Kubelet (real subprocesses) + topology/gang scheduler tests."""

import sys

from kubeflow_tpu.core.cluster import Cluster
from kubeflow_tpu.scheduler.topology import (
    POD_GROUP_LABEL,
    TPU_RESOURCE,
    chips_in,
    make_tpu_slice,
    parse_quantity,
    slice_shape,
)


def py_pod(name, code, ns="default", labels=None, restart="Never", resources=None):
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns, "labels": labels or {}},
        "spec": {
            "restartPolicy": restart,
            "containers": [
                {
                    "name": "main",
                    "command": [sys.executable, "-u", "-c", code],
                    "resources": resources or {},
                }
            ],
        },
    }


def phase(cluster, name, ns="default"):
    pod = cluster.api.try_get("Pod", name, ns)
    return pod.get("status", {}).get("phase") if pod else None


def test_pod_runs_to_success_and_logs(cluster):
    cluster.api.create(py_pod("hello", "print('hello from pod')"))
    assert cluster.wait_for(lambda: phase(cluster, "hello") == "Succeeded", timeout=30)
    assert "hello from pod" in cluster.logs("hello")


def test_sidecar_container_flushes_before_pod_terminal(cluster):
    """containers[1:] run as sidecars: started with the main container,
    SIGTERMed after it exits, with the pod only going terminal once the
    sidecar's shutdown work (here: copying the main log) finished — the
    contract the Katib push metrics collector relies on."""
    import os
    import tempfile

    marker = os.path.join(tempfile.mkdtemp(), "sidecar-out.txt")
    sidecar_code = (
        "import os, signal, time\n"
        "stop = {'now': False}\n"
        "signal.signal(signal.SIGTERM, lambda *_: stop.update(now=True))\n"
        "while not stop['now'] and not os.path.exists(os.environ['POD_STOP_FILE']):\n"
        "    time.sleep(0.05)\n"
        f"open({marker!r}, 'w').write(open(os.environ['POD_LOG_PATH']).read())\n"
    )
    pod = py_pod("with-sidecar", "print('main says metric=1.0')")
    pod["spec"]["containers"].append({
        "name": "tail",
        "command": [sys.executable, "-u", "-c", sidecar_code],
    })
    cluster.api.create(pod)
    assert cluster.wait_for(lambda: phase(cluster, "with-sidecar") == "Succeeded", timeout=30)
    # phase flipped terminal only after the sidecar's SIGTERM handler ran
    with open(marker) as f:
        assert "main says metric=1.0" in f.read()


def test_pod_failure_exit_code_recorded(cluster):
    cluster.api.create(py_pod("boom", "import sys; sys.exit(3)"))
    assert cluster.wait_for(lambda: phase(cluster, "boom") == "Failed", timeout=30)
    st = cluster.api.get("Pod", "boom")["status"]["containerStatuses"][0]
    assert st["state"]["terminated"]["exitCode"] == 3


def test_init_containers_run_before_main(cluster):
    pod = py_pod("withinit", "print('MAIN')")
    pod["spec"]["initContainers"] = [
        {"name": "init", "command": [sys.executable, "-u", "-c", "print('INIT')"]}
    ]
    cluster.api.create(pod)
    assert cluster.wait_for(lambda: phase(cluster, "withinit") == "Succeeded", timeout=30)
    log = cluster.logs("withinit")
    assert log.index("INIT") < log.index("MAIN")


def test_on_failure_restart(cluster):
    # fails first run, succeeds after a marker file exists
    code = (
        "import os,sys\n"
        "m = os.environ['MARKER']\n"
        "if not os.path.exists(m):\n"
        "    open(m,'w').close(); sys.exit(1)\n"
        "print('second run ok')\n"
    )
    import tempfile

    marker = tempfile.mktemp()
    pod = py_pod("flaky", code, restart="OnFailure")
    pod["spec"]["containers"][0]["env"] = [{"name": "MARKER", "value": marker}]
    cluster.api.create(pod)
    assert cluster.wait_for(lambda: phase(cluster, "flaky") == "Succeeded", timeout=30)
    st = cluster.api.get("Pod", "flaky")["status"]["containerStatuses"][0]
    assert st["restartCount"] == 1


def test_pod_delete_kills_process(cluster):
    cluster.api.create(py_pod("sleeper", "import time; time.sleep(300)"))
    assert cluster.wait_for(lambda: phase(cluster, "sleeper") == "Running", timeout=30)
    cluster.api.delete("Pod", "sleeper")
    kubelet = cluster.kubelets["cpu-0"]
    assert cluster.wait_for(lambda: not kubelet._runs, timeout=30)


def test_quantity_parsing():
    assert parse_quantity("500m") == 0.5
    assert parse_quantity("2") == 2.0
    assert parse_quantity("1Gi") == 2**30
    assert parse_quantity("1.5G") == 1.5e9
    assert parse_quantity(4) == 4.0


def test_slice_shapes():
    assert chips_in("4x4") == 16
    assert slice_shape("v5e", 16) == "4x4"
    assert chips_in(slice_shape("v4", 32)) == 32


def test_tpu_slice_nodes_and_gang_all_or_nothing():
    c = Cluster(cpu_nodes=0, tpu_slices=(("s0", "v5e", "2x4"),))  # 8 chips, 2 hosts
    try:
        assert len(c.api.list("Node")) == 2
        # gang of 2 pods, each wanting 4 chips: fits on the slice (one per host)
        c.api.create({"apiVersion": "scheduling.kubeflow.org/v1", "kind": "PodGroup",
                      "metadata": {"name": "g"}, "spec": {"minMember": 2}})
        for i in range(2):
            c.api.create(py_pod(f"w-{i}", "print('ok')",
                                labels={POD_GROUP_LABEL: "g"},
                                resources={"requests": {TPU_RESOURCE: 4}}))
        assert c.wait_for(lambda: all(phase(c, f"w-{i}") == "Succeeded" for i in range(2)), timeout=30)
        nodes = {c.api.get("Pod", f"w-{i}")["spec"]["nodeName"] for i in range(2)}
        assert nodes == {"s0-host-0", "s0-host-1"}
    finally:
        c.shutdown()


def test_gang_does_not_bind_partial():
    c = Cluster(cpu_nodes=0, tpu_slices=(("s0", "v5e", "2x2"),))  # 4 chips, 1 host
    try:
        c.api.create({"apiVersion": "scheduling.kubeflow.org/v1", "kind": "PodGroup",
                      "metadata": {"name": "g"}, "spec": {"minMember": 2}})
        for i in range(2):
            c.api.create(py_pod(f"w-{i}", "print('ok')",
                                labels={POD_GROUP_LABEL: "g"},
                                resources={"requests": {TPU_RESOURCE: 4}}))
        c.settle(quiet=0.3, timeout=10)
        # infeasible gang (needs 8 chips, slice has 4): NOTHING binds
        for i in range(2):
            assert not c.api.get("Pod", f"w-{i}")["spec"].get("nodeName")
        pg = c.api.get("PodGroup", "g")
        assert pg["status"]["phase"] == "Pending"
    finally:
        c.shutdown()


def test_create_pod_keeps_stop_file_of_draining_incarnation(cluster):
    """A same-named replacement pod must not unlink the stop file of a
    previous incarnation that is STILL draining (uid present in _runs) —
    its sidecars rely on the stop file for the race-free exit signal;
    only truly orphaned uids are litter (ADVICE r3)."""
    import os
    import sys as _sys

    wait_code = (
        "import os, time\n"
        "while not os.path.exists(os.environ['POD_STOP_FILE']):\n"
        "    time.sleep(0.05)\n"
    )
    cluster.api.create(py_pod("dup", wait_code))
    assert cluster.wait_for(lambda: phase(cluster, "dup") == "Running", timeout=30)
    uid1 = cluster.api.get("Pod", "dup")["metadata"]["uid"]
    kubelet = next(k for k in cluster.kubelets.values() if uid1 in k._runs)
    run1 = kubelet._runs[uid1]
    # old incarnation mid-drain: its stop file is live on disk
    open(run1.stop_path, "w").close()
    # plus a genuinely orphaned stop file from a long-reaped run
    orphan = run1.log_path + ".deadbeef.stop"
    open(orphan, "w").close()
    # a same-named replacement starts while uid1 is still draining
    pod2 = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "dup", "namespace": "default", "uid": "uid-2"},
        "spec": {"restartPolicy": "Never", "containers": [
            {"name": "main",
             "command": [_sys.executable, "-u", "-c", "print('v2')"],
             "resources": {}}]},
    }
    run2 = kubelet._start(pod2)
    try:
        assert os.path.exists(run1.stop_path), \
            "live incarnation's stop file was unlinked by the replacement"
        assert not os.path.exists(orphan), "orphaned stop file not cleaned"
    finally:
        kubelet._terminate(run2, grace=0.5)
        kubelet._runs.pop("uid-2", None)
        os.unlink(run1.stop_path)
