"""Self-driving fleet tests (README "Self-driving fleet",
serving/remediator.py).

Coverage per the ISSUE 17 satellite list:

  * the ``faults.EXPECTED_REMEDIATIONS`` contract: every chaos class maps
    chaos -> cause -> playbook, in lockstep with the incident taxonomy
    and the remediator's own ``CAUSE_PLAYBOOK`` table;
  * per-playbook rails with explicit clocks: cooldown defers (then
    retries), the global rate budget throttles, starvation past
    ``defer_max`` escalates, the flap guard escalates the same
    (cause, target) to needs_human and stays sticky, dry-run annotates
    the full plan with ZERO actuator calls;
  * single-writer arbitration: the remediator never patches
    ``spec.replicas`` — it proposes floors, the autoscaler's next sync
    applies them exactly once (no double-scale), and proposals TTL out;
  * quarantine round trips: probe-streak-gated un-quarantine (one bad
    probe resets the streak), FabricStore/HandoffStore enforcement
    (refused publishes/pulls while quarantined, resident entries serve
    again after the lift);
  * the refined scale-down veto: only UNREMEDIATED open incidents veto,
    in-flight/escalated remediation releases it, and the veto is bounded
    by ``INCIDENT_VETO_MAX_HOLD_S``;
  * predictive prescale: the seeded storm envelope is forecast
    deterministically, the floor is proposed BEFORE the burst trips, and
    an unchanged forecast is never re-proposed;
  * e2e, one-fault -> one-incident -> one-action -> one-closed-bundle
    for every taxonomy cause (explicit-clock managers for the per-cause
    battery; a real ServiceProxy + failover storm for the ingress path,
    GET /fleet/remediation included).
"""

import copy
import json
import time
import urllib.request
from pathlib import Path

import pytest

from kubeflow_tpu.core.api import APIServer
from kubeflow_tpu.serving import incidents as I
from kubeflow_tpu.serving import remediator as R
from kubeflow_tpu.serving.api import (LABEL_ISVC,
                                      TARGET_CONCURRENCY_ANNOTATION)
from kubeflow_tpu.serving.autoscaler import ConcurrencyAutoscaler
from kubeflow_tpu.serving.controllers import (
    DEPLOYMENT_FOR_SERVICE_ANNOTATION, POD_PORT_ANNOTATION,
    PROXY_PORT_ANNOTATION)
from kubeflow_tpu.serving.disagg import (DISAGG_ANNOTATION, ROLE_ANNOTATION,
                                         HandoffStore, pod_role)
from kubeflow_tpu.serving.engine.faults import (EXPECTED_INCIDENT_CAUSES,
                                                EXPECTED_REMEDIATIONS,
                                                StormFaultConfig)
from kubeflow_tpu.serving.kvfabric import FabricStore

pytestmark = pytest.mark.remediation


def _wait(pred, timeout=30.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    raise TimeoutError(f"timed out waiting for {msg}")


# --------------------------------------------------------------- test doubles


class _StubMgr:
    """An incident source the rails tests drive with synthetic incident
    dicts — annotations are recorded, never re-clocked, so the tests own
    every timestamp."""

    def __init__(self, *incidents):
        self.incidents = list(incidents)
        self.annotations = []  # (incident_id, action, status)

    def list(self):
        return [copy.deepcopy(i) for i in self.incidents]

    def annotate_remediation(self, incident_id, action, status=None):
        if not any(i["id"] == incident_id for i in self.incidents):
            return False
        self.annotations.append((incident_id, dict(action), status))
        return True


class _AscSpy:
    """Records floor proposals; never scales anything."""

    def __init__(self):
        self.calls = []  # (deployment, floor)

    def propose_floor(self, deployment, replicas, ttl_s=30.0, reason=""):
        self.calls.append((deployment, int(replicas)))

    def proposals(self):
        return {}


def _inc(inc_id, cause, scope="ingress:svc", symptoms=()):
    return {"id": inc_id, "state": "open", "cause": cause, "scope": scope,
            "symptoms": list(symptoms)}


def _deployment(name="d", replicas=2, target="4"):
    return {"apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": name,
                         "annotations": {
                             TARGET_CONCURRENCY_ANNOTATION: target}},
            "spec": {"replicas": replicas,
                     "selector": {"matchLabels": {"app": name}},
                     "template": {"metadata": {"labels": {"app": name}},
                                  "spec": {"containers": [
                                      {"name": "c", "command": ["x"]}]}}}}


def _service(name="svc", deployments=("d",), extra_ann=None):
    ann = {DEPLOYMENT_FOR_SERVICE_ANNOTATION: json.dumps(list(deployments))}
    ann.update(extra_ann or {})
    return {"apiVersion": "v1", "kind": "Service",
            "metadata": {"name": name, "annotations": ann,
                         "labels": {LABEL_ISVC: name}},
            "spec": {"selector": {"app": name}}}


def _pod(name, role=None, ready=True, port=None):
    ann = {}
    if role is not None:
        ann[ROLE_ANNOTATION] = role
    if port is not None:
        ann[POD_PORT_ANNOTATION] = str(port)
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "labels": {"app": "svc"},
                         "annotations": ann},
            "spec": {},
            "status": {"phase": "Running",
                       "conditions": [{"type": "Ready",
                                       "status": "True" if ready
                                       else "False"}]}}


def _api_with(*objs):
    api = APIServer()
    for o in objs:
        api.create(o)
    return api


def _cfg(**kw):
    base = dict(cooldown_s=0.0, rate_budget=100, rate_window_s=60.0,
                flap_max=100, flap_window_s=60.0)
    base.update(kw)
    return R.RemediatorConfig(**base)


# ----------------------------------------------------------------- contract


def test_expected_remediations_contract():
    """chaos class -> cause -> playbook, one table, no drift: every
    chaos class the repo can inject names the cause the incident plane
    classifies AND the playbook the remediator runs for it."""
    assert set(EXPECTED_REMEDIATIONS) == set(EXPECTED_INCIDENT_CAUSES)
    for key, spec in EXPECTED_REMEDIATIONS.items():
        assert spec["cause"] == EXPECTED_INCIDENT_CAUSES[key]
        assert spec["playbook"] == R.CAUSE_PLAYBOOK[spec["cause"]], key
        assert spec["playbook"] in R.PLAYBOOKS
    # the playbook table covers the incident taxonomy exactly
    assert set(R.CAUSE_PLAYBOOK) == set(I.CAUSES)


# ------------------------------------------------------------------- rails


def test_cooldown_defers_then_executes():
    """Two same-playbook incidents in one pass: the second waits out the
    per-playbook cooldown, then the rescan retries and executes it —
    deferred, never dropped."""
    mgr = _StubMgr(_inc("a", "capacity", scope="ingress:s1"),
                   _inc("b", "capacity", scope="ingress:s2"))
    asc = _AscSpy()
    r = R.FleetRemediator(api=_api_with(_deployment()), autoscaler=asc,
                          config=_cfg(cooldown_s=5.0))
    r.attach(mgr)
    r._process(1000.0)
    assert len(asc.calls) == 1  # a executed, b cooling
    r._process(1004.0)
    assert len(asc.calls) == 1  # still inside the cooldown
    r._process(1006.0)
    assert len(asc.calls) == 2  # cooldown over -> b executed
    # b's bundle named the PLANNED action while it waited (an incident
    # may self-resolve mid-deferral; its postmortem must not be empty)
    assert [s for _, _, s in mgr.annotations] \
        == ["in_flight", "deferred", "in_flight"]
    assert mgr.annotations[1][1]["playbook"] == "prescale"


def test_rate_budget_throttles_across_playbooks():
    """At most rate_budget executed actions per window, globally."""
    mgr = _StubMgr(*[_inc(f"i{k}", "capacity", scope=f"ingress:s{k}")
                     for k in range(3)])
    asc = _AscSpy()
    r = R.FleetRemediator(api=_api_with(_deployment()), autoscaler=asc,
                          config=_cfg(rate_budget=2))
    r.attach(mgr)
    r._process(2000.0)
    assert len(asc.calls) == 2  # budget spent; third deferred
    r._process(2001.0)
    assert len(asc.calls) == 2  # window still open
    r._process(2070.0)          # window rolled off
    assert len(asc.calls) == 3


def test_starved_incident_escalates_past_defer_max():
    """A budget that never frees must not leave the bundle silently
    open: past defer_max deferrals the incident escalates."""
    mgr = _StubMgr(_inc("a", "capacity"))
    r = R.FleetRemediator(api=_api_with(_deployment()),
                          autoscaler=_AscSpy(),
                          config=_cfg(rate_budget=0, defer_max=2))
    r.attach(mgr)
    r._process(0.0)  # first deferral marks the planned action
    assert [s for _, _, s in mgr.annotations] == ["deferred"]
    assert mgr.annotations[0][1]["playbook"] == "prescale"
    r._process(1.0)  # repeat deferrals stay silent
    assert len(mgr.annotations) == 1
    r._process(2.0)  # deferrals exceed defer_max
    assert len(mgr.annotations) == 2
    _, action, status = mgr.annotations[-1]
    assert status == "escalated"
    assert action["playbook"] == "needs_human"
    assert "starved" in action["detail"]["reason"]


def test_flap_guard_escalates_and_sticks():
    """The same (cause, target) remediated flap_max times inside the
    window escalates to needs_human instead of oscillating, stays
    escalated for the window, and resumes after it rolls off."""
    asc = _AscSpy()
    mgr = _StubMgr()
    r = R.FleetRemediator(api=_api_with(_deployment()), autoscaler=asc,
                          config=_cfg(flap_max=2))
    r.attach(mgr)
    esc0 = R.INCIDENTS_ESCALATED.value(cause="capacity")
    for k, t in ((1, 0.0), (2, 1.0)):
        mgr.incidents.append(_inc(f"i{k}", "capacity", scope="ingress:s1"))
        r._process(t)
    assert len(asc.calls) == 2
    mgr.incidents.append(_inc("i3", "capacity", scope="ingress:s1"))
    r._process(2.0)
    assert len(asc.calls) == 2  # escalated, not executed
    assert r.escalations == 1
    assert R.INCIDENTS_ESCALATED.value(cause="capacity") == esc0 + 1
    _, action, status = mgr.annotations[-1]
    assert (status, action["playbook"]) == ("escalated", "needs_human")
    # sticky inside the window: the next incident on the key escalates too
    mgr.incidents.append(_inc("i4", "capacity", scope="ingress:s1"))
    r._process(3.0)
    assert r.escalations == 2 and len(asc.calls) == 2
    # the window rolls off -> the playbook runs again
    mgr.incidents.append(_inc("i5", "capacity", scope="ingress:s1"))
    r._process(70.0)
    assert len(asc.calls) == 3


def test_dry_run_annotates_with_zero_actuator_calls():
    """Dry-run resolves the full plan for every playbook — floors, role
    flips, quarantine target — and makes ZERO actuator calls; the bundle
    log reads exactly like a live run."""
    api = _api_with(_deployment(),
                    _service(extra_ann={DISAGG_ANNOTATION: "auto"}),
                    _pod("p0"), _pod("p1"))
    patches = []
    orig = api.patch
    api.patch = lambda *a, **k: (patches.append(a[0]), orig(*a, **k))[1]
    asc = _AscSpy()
    mgr = _StubMgr(_inc("c1", "capacity"),
                   _inc("c2", "prefill_interference", scope="engine:m"),
                   _inc("c3", "storage_degradation"))
    r = R.FleetRemediator(api=api, autoscaler=asc,
                          config=_cfg(dry_run=True))
    r.attach(mgr)
    dry0 = R.REMEDIATION_ACTIONS.value(playbook="prescale",
                                       outcome="dry_run")
    r._process(100.0)
    assert asc.calls == []
    assert patches == []
    assert r.quarantine.list() == {}
    assert len(mgr.annotations) == 3
    for _, action, status in mgr.annotations:
        assert status == "dry_run"
        assert action["outcome"] == "dry_run"
        assert action["dry_run"] is True
    by_id = {i: a for i, a, _ in mgr.annotations}
    assert by_id["c1"]["detail"]["proposals"][0]["proposed_floor"] == 3
    assert [f["role"] for f in by_id["c2"]["detail"]["flips"]] \
        == ["prefill", "decode"]
    assert by_id["c3"]["detail"]["tier"] == "storage"
    assert R.REMEDIATION_ACTIONS.value(
        playbook="prescale", outcome="dry_run") == dry0 + 1
    # the rails advanced: a second pass re-runs nothing
    r._process(101.0)
    assert len(mgr.annotations) == 3


# ------------------------------------------------------------- arbitration


def test_arbitration_remediator_never_writes_replicas(monkeypatch):
    """Single-writer: the remediator only PROPOSES; the autoscaler's
    sync applies the floor exactly once — never a second time for the
    same proposal — and _scale() stays the only spec.replicas writer."""
    api = _api_with(
        _deployment(replicas=1),
        _service(),
    )
    asc = ConcurrencyAutoscaler(api)
    mgr = _StubMgr(_inc("rd", "replica_death", symptoms=[
        {"kind": "breaker_open", "backend": "127.0.0.1:9"}]))
    r = R.FleetRemediator(api=api, autoscaler=asc, config=_cfg())
    r.attach(mgr)
    patched_kinds = []
    orig = api.patch
    api.patch = lambda *a, **k: (patched_kinds.append(a[0]),
                                 orig(*a, **k))[1]
    r._process(1000.0)
    # the remediator touched NOTHING — no Deployment (or any) patches
    assert patched_kinds == []
    assert asc.proposals()["d"]["floor"] == 2
    assert "replace_replica" in asc.proposals()["d"]["reason"]
    assert mgr.annotations[0][1]["detail"]["ejected_backends"] \
        == ["127.0.0.1:9"]
    # the autoscaler applies it, once
    assert asc.sync()
    assert api.get("Deployment", "d")["spec"]["replicas"] == 2
    assert patched_kinds.count("Deployment") == 1
    # a second sync with the same standing proposal does NOT double-scale
    asc.sync()
    assert api.get("Deployment", "d")["spec"]["replicas"] == 2
    assert patched_kinds.count("Deployment") == 1


def test_arbitration_proposals_expire():
    """A dead remediator cannot pin fleet size: proposals TTL out and
    the sync prunes them."""
    api = _api_with(_deployment(replicas=2))
    asc = ConcurrencyAutoscaler(api)
    asc.propose_floor("d", 3, ttl_s=0.01)
    time.sleep(0.05)
    assert asc.proposals() == {}
    asc.sync()
    assert api.get("Deployment", "d")["spec"]["replicas"] == 2


def test_proposal_clamped_to_max_replicas():
    """A proposed floor above maxReplicas is clamped, never applied
    raw (default max is 3)."""
    api = _api_with(_deployment(replicas=1))
    asc = ConcurrencyAutoscaler(api)
    asc.propose_floor("d", 50, ttl_s=30.0)
    asc.sync()
    assert api.get("Deployment", "d")["spec"]["replicas"] == 3


# -------------------------------------------------------------- quarantine


def test_quarantine_probe_streak_gates_unquarantine():
    """healthy_probes CONSECUTIVE healthy reads lift; one bad probe
    resets the streak."""
    mgr = _StubMgr(_inc("q1", "storage_degradation"))
    r = R.FleetRemediator(config=_cfg(probe_interval_s=0.0,
                                      healthy_probes=2))
    r.attach(mgr)
    reads = [True, False, True, True]
    enforced = []
    r.quarantine.register("storage", enforce=enforced.append,
                          probe=lambda: reads.pop(0))
    r._process(0.0)   # quarantine + probe: healthy (streak 1)
    assert r.quarantine.active("storage")
    assert enforced == [True]
    assert R.REMEDIATION_QUARANTINED.value(tier="storage") == 1.0
    r._process(1.0)   # unhealthy -> streak resets to 0
    r._process(2.0)   # healthy (streak 1)
    assert r.quarantine.active("storage")
    r._process(3.0)   # healthy (streak 2) -> lift
    assert not r.quarantine.active("storage")
    assert enforced == [True, False]
    assert R.REMEDIATION_QUARANTINED.value(tier="storage") == 0.0
    lifted = [a for a in r.status()["actions"] if a["outcome"] == "lifted"]
    assert len(lifted) == 1 and lifted[0]["target"] == "storage"


def test_fabric_store_quarantine_enforcement():
    """A quarantined FabricStore refuses publishes, answers every pull
    as the CLOSED-vocabulary 'miss', hides coverage and its view — and
    serves resident entries again the moment the quarantine lifts."""
    fs = FabricStore()
    assert fs.publish("k", b"frame", {"pages": 2})
    fs.set_quarantined(True)
    assert fs.quarantined()
    assert fs.pull("k") == ("miss", None)
    assert not fs.publish("k2", b"x", {"pages": 1})
    assert not fs.covers("k", 1)
    assert fs.view() == []
    assert fs.quarantine_refusals == 2
    assert fs.stats()["quarantined"] is True
    fs.set_quarantined(False)
    outcome, data = fs.pull("k")
    assert (outcome, data) == ("ok", b"frame")  # entry stayed resident


def test_handoff_store_quarantine_enforcement():
    hs = HandoffStore()
    handle = hs.put(b"kv", {"pages": 1})
    assert handle is not None
    hs.set_quarantined(True)
    assert hs.pull(handle) == ("miss", None)
    assert hs.put(b"kv2", {"pages": 1}) is None
    assert hs.quarantine_refusals == 2
    hs.set_quarantined(False)
    outcome, data = hs.pull(handle)
    assert (outcome, data) == ("ok", b"kv")  # exported frame survived


# -------------------------------------------------------- scale-down veto


class _VetoMgr:
    """Stub exposing BOTH counts: open incidents whose remediation is in
    flight keep open_count high while unremediated_open_count drops."""

    def __init__(self, unremediated=1):
        self.unremediated = unremediated

    def open_count(self):
        return 1

    def unremediated_open_count(self):
        return self.unremediated

    def feed(self, *a, **k):
        pass


def test_scale_down_veto_releases_when_remediation_in_flight(monkeypatch):
    from kubeflow_tpu.serving import autoscaler as asc_mod

    mgr = _VetoMgr(unremediated=1)
    api = _api_with(_deployment(replicas=3))
    a = ConcurrencyAutoscaler(api, incidents=mgr)
    monkeypatch.setattr(asc_mod, "SCALE_DOWN_WINDOW", 0.0)
    # an unremediated open incident vetoes every shrink
    for _ in range(3):
        assert not a.sync()
    assert api.get("Deployment", "d")["spec"]["replicas"] == 3
    # its playbook goes in-flight (open_count stays 1!) -> veto released
    mgr.unremediated = 0
    a.sync()                 # arms the (zeroed) stability window
    assert a.sync()
    assert api.get("Deployment", "d")["spec"]["replicas"] == 1


def test_scale_down_veto_bounded_by_max_hold(monkeypatch):
    """An incident nobody can remediate (and that refuses to resolve)
    must not pin the fleet size past INCIDENT_VETO_MAX_HOLD_S."""
    from kubeflow_tpu.serving import autoscaler as asc_mod

    mgr = _VetoMgr(unremediated=1)  # never remediated, never resolves
    api = _api_with(_deployment(replicas=3))
    a = ConcurrencyAutoscaler(api, incidents=mgr)
    monkeypatch.setattr(asc_mod, "SCALE_DOWN_WINDOW", 0.0)
    monkeypatch.setattr(asc_mod, "INCIDENT_VETO_MAX_HOLD_S", 0.0)
    a.sync()                 # hold expired instantly -> window arms
    assert a.sync()
    assert api.get("Deployment", "d")["spec"]["replicas"] == 1


def test_unremediated_open_count_statuses(tmp_path):
    """The real manager's refined count: dry_run/observing/none still
    veto (nobody is ACTING), in_flight and escalated do not."""
    mgr = I.IncidentManager(
        "t", I.IncidentConfig(bundle_dir=str(tmp_path)),
        detectors=I.engine_detectors())
    mgr.feed("watchdog", detail="died", trace_ids=[])
    mgr._process(time.monotonic())
    inc_id = mgr.list()[0]["id"]
    assert mgr.open_count() == 1
    assert mgr.unremediated_open_count() == 1
    action = {"playbook": "replace_replica", "outcome": "dry_run"}
    assert mgr.annotate_remediation(inc_id, action, status="dry_run")
    assert mgr.unremediated_open_count() == 1  # annotated, not acted on
    mgr.annotate_remediation(inc_id, action, status="in_flight")
    assert mgr.unremediated_open_count() == 0
    mgr.annotate_remediation(inc_id, action, status="escalated")
    assert mgr.unremediated_open_count() == 0  # a human owns it now
    assert mgr.open_count() == 1
    assert not mgr.annotate_remediation("inc-nope", action)


# ------------------------------------------------------ predictive prescale


def test_forecast_proposes_before_the_burst():
    """The seeded storm envelope is deterministic, so the remediator
    proposes the burst's floor BEFORE the burst trips — and never
    re-proposes an unchanged forecast."""
    storm = StormFaultConfig(duration_s=100.0, base_qps=4.0,
                             diurnal_period_s=0.0, diurnal_depth=0.0,
                             burst_every_s=10.0, burst_len_s=2.0,
                             burst_x=3.0)
    # the envelope itself: flat 4 qps, x3 inside [k*10, k*10+2)
    assert R.storm_rate_qps(storm, 5.0) == 4.0
    assert R.storm_rate_qps(storm, 10.5) == 12.0
    assert R.forecast_peak_qps(storm, 3.0, 2.0) == 4.0
    assert R.forecast_peak_qps(storm, 8.75, 2.0) == 12.0
    asc = _AscSpy()
    r = R.FleetRemediator(autoscaler=asc, config=_cfg(
        forecast_horizon_s=2.0, forecast_headroom=1.2))
    r.set_forecast(storm, per_replica_qps=2.0, deployment="d", t0=1000.0)
    r._process(1003.0)   # quiet stretch: ceil(4 * 1.2 / 2) = 3
    assert asc.calls == [("d", 3)]
    r._process(1007.0)   # forecast unchanged -> no re-proposal
    assert asc.calls == [("d", 3)]
    r._process(1008.75)  # burst at t=10 enters the horizon -> pre-scale
    assert asc.calls == [("d", 3), ("d", 8)]  # ceil(12 * 1.2 / 2) = 8
    proposed = [a for a in r.status()["actions"]
                if a["outcome"] == "proposed"]
    assert [a["detail"]["proposed_floor"] for a in proposed] == [3, 8]
    assert proposed[-1]["detail"]["t_s"] < 10.0  # before the burst
    r.clear_forecast()
    r._process(1009.0)
    assert len(asc.calls) == 2
    assert r.status()["forecast_armed"] is False


# ------------------------------------- e2e: one closed bundle per cause


def _live_mgr(tmp_path, scope, detectors):
    return I.IncidentManager(
        scope, I.IncidentConfig(debounce_s=0.1, resolve_s=0.1,
                                bundle_dir=str(tmp_path)),
        detectors=detectors)


def _assert_closed_bundle(mgr, tmp_path, inc_id, playbook):
    """The postmortem contract: the incident resolved, its bundle names
    the remediation, and the timeline reads detector -> classification
    -> remediation -> resolution."""
    mgr._process(time.monotonic() + 0.3)  # quiet window -> resolve
    inc = mgr.get(inc_id)
    assert inc["state"] == "resolved"
    assert inc["remediation"]["playbook"] == playbook
    steps = [row["step"] for row in I.timeline(inc)]
    assert steps.index("classified") < steps.index("remediation") \
        < steps.index("resolved")
    bundles = [json.loads(p.read_text())
               for p in Path(tmp_path).glob("*.json")]
    mine = [b for b in bundles if b.get("id") == inc_id]
    assert mine and mine[0]["remediation"]["playbook"] == playbook
    assert mine[0]["state"] == "resolved"


def test_e2e_replica_death_replaces_replica(tmp_path):
    mgr = _live_mgr(tmp_path, "ingress:svc", I.ingress_detectors())
    asc = _AscSpy()
    r = R.FleetRemediator(api=_api_with(_deployment(), _service()),
                          autoscaler=asc, config=_cfg())
    r.attach(mgr)
    mgr.feed("breaker_open", backend="127.0.0.1:9", trace_ids=[])
    mgr._process(time.monotonic())
    inc = mgr.list()[0]
    assert inc["cause"] == "replica_death"
    r._process(time.monotonic())
    assert asc.calls == [("d", 3)]  # current 2 + prewarm_extra 1
    rem = mgr.get(inc["id"])["remediation"]
    assert rem["status"] == "in_flight"
    assert rem["actions"][0]["detail"]["ejected_backends"] \
        == ["127.0.0.1:9"]
    _assert_closed_bundle(mgr, tmp_path, inc["id"], "replace_replica")


def test_e2e_prefill_interference_splits_roles(tmp_path):
    mgr = _live_mgr(tmp_path, "engine:m", I.engine_detectors())
    api = _api_with(_service(extra_ann={DISAGG_ANNOTATION: "auto"}),
                    _pod("p0"), _pod("p1"), _pod("p2"))
    r = R.FleetRemediator(api=api, config=_cfg())
    r.attach(mgr)
    mgr.feed("slo_burn", metric="tpot", class_name="interactive",
             prefill_active=2, trace_ids=[])
    mgr._process(time.monotonic())
    inc = mgr.list()[0]
    assert inc["cause"] == "prefill_interference"
    r._process(time.monotonic())
    # the two lowest-named unified pods flipped to a prefill/decode pair
    assert pod_role(api.get("Pod", "p0")) == "prefill"
    assert pod_role(api.get("Pod", "p1")) == "decode"
    assert pod_role(api.get("Pod", "p2")) == "unified"
    _assert_closed_bundle(mgr, tmp_path, inc["id"], "split_roles")


def test_e2e_split_roles_keeps_last_unified_replica(tmp_path):
    """One unified replica left: flipping it would leave no pool able to
    serve the complementary phase — the playbook refuses."""
    mgr = _live_mgr(tmp_path, "engine:m", I.engine_detectors())
    api = _api_with(_service(extra_ann={DISAGG_ANNOTATION: "auto"}),
                    _pod("p0"), _pod("p1", role="decode"))
    r = R.FleetRemediator(api=api, config=_cfg())
    r.attach(mgr)
    mgr.feed("slo_burn", metric="tpot", prefill_active=1, trace_ids=[])
    mgr._process(time.monotonic())
    inc_id = mgr.list()[0]["id"]
    r._process(time.monotonic())
    assert pod_role(api.get("Pod", "p0")) == "unified"  # untouched
    rem = mgr.get(inc_id)["remediation"]
    assert rem["status"] == "failed"
    assert rem["actions"][0]["outcome"] == "skipped"


def test_e2e_split_roles_refuses_without_disagg_routing(tmp_path):
    """No Service routes the disagg split: prefill-role pods would take
    no traffic at all, so flipping roles only shrinks the unified pool.
    The playbook refuses and says why (the --campaign bench measured
    exactly this regression before the guard existed)."""
    mgr = _live_mgr(tmp_path, "engine:m", I.engine_detectors())
    api = _api_with(_service(), _pod("p0"), _pod("p1"))  # disagg off
    r = R.FleetRemediator(api=api, config=_cfg())
    r.attach(mgr)
    mgr.feed("slo_burn", metric="tpot", prefill_active=1, trace_ids=[])
    mgr._process(time.monotonic())
    inc_id = mgr.list()[0]["id"]
    r._process(time.monotonic())
    assert pod_role(api.get("Pod", "p0")) == "unified"  # untouched
    rem = mgr.get(inc_id)["remediation"]
    assert rem["status"] == "failed"
    assert rem["actions"][0]["outcome"] == "skipped"
    assert "disagg" in rem["actions"][0]["detail"]["reason"]


def test_e2e_capacity_prescales(tmp_path):
    mgr = _live_mgr(tmp_path, "ingress:svc", I.ingress_detectors())
    asc = _AscSpy()
    r = R.FleetRemediator(api=_api_with(_deployment(), _service()),
                          autoscaler=asc, config=_cfg())
    r.attach(mgr)
    mgr.feed("shed", class_name="batch", shed=7, trace_ids=[])
    mgr._process(time.monotonic())
    inc = mgr.list()[0]
    assert inc["cause"] == "capacity"
    r._process(time.monotonic())
    assert asc.calls == [("d", 3)]  # current 2 + 1
    _assert_closed_bundle(mgr, tmp_path, inc["id"], "prescale")


@pytest.mark.parametrize("source", ["storage", "handoff", "fabric"])
def test_e2e_degradation_quarantines_and_lifts(tmp_path, source):
    """degradation -> quarantine -> incident resolves -> the DEFAULT
    probe (tier cause quiet across attached managers) lifts it after
    healthy_probes consecutive reads."""
    mgr = _live_mgr(tmp_path, "engine:m", I.engine_detectors())
    r = R.FleetRemediator(config=_cfg(probe_interval_s=0.0,
                                      healthy_probes=2))
    r.attach(mgr)
    mgr.feed("degradation", source=source, outcome="recompute",
             trace_ids=[])
    mgr._process(time.monotonic())
    inc = mgr.list()[0]
    assert inc["cause"] == f"{source}_degradation"
    now = time.monotonic()
    r._process(now)  # quarantine + first probe (incident open: unhealthy)
    assert r.quarantine.active(source)
    r._process(now + 1)
    assert r.quarantine.active(source)  # still open -> streak stays 0
    _assert_closed_bundle(mgr, tmp_path, inc["id"], "quarantine_tier")
    r._process(now + 2)  # quiet: healthy 1
    assert r.quarantine.active(source)
    r._process(now + 3)  # healthy 2 -> lift
    assert not r.quarantine.active(source)
    lifted = [a for a in r.status()["actions"]
              if a["outcome"] == "lifted"]
    assert lifted and lifted[-1]["target"] == source


def test_e2e_unknown_cause_observes(tmp_path):
    """A cause no rule names gets watched, not 'fixed': observe
    annotates and touches nothing."""
    mgr = _live_mgr(tmp_path, "engine:m", I.engine_detectors())
    asc = _AscSpy()
    r = R.FleetRemediator(api=_api_with(_deployment()), autoscaler=asc,
                          config=_cfg())
    r.attach(mgr)
    mgr.feed("nan_guard", detail="nan in logits", trace_ids=[])
    mgr._process(time.monotonic())
    inc = mgr.list()[0]
    assert inc["cause"] == "unknown"
    r._process(time.monotonic())
    assert asc.calls == []
    assert r.quarantine.list() == {}
    assert mgr.get(inc["id"])["remediation"]["status"] == "observing"
    _assert_closed_bundle(mgr, tmp_path, inc["id"], "observe")


def test_fleet_remediation_endpoint_over_failover():
    """End to end through the real service proxy with live threads: a
    500ing backend drives failover -> replica_death; the attached
    remediator proposes a pre-warm floor (annotating the live bundle),
    the autoscaler applies it, and GET /fleet/remediation serves the
    action log + the in-flight proposals.  Zero human actions."""
    from kubeflow_tpu.serving.router import ServiceProxy
    from kubeflow_tpu.serving.server import Model, ModelServer
    from kubeflow_tpu.utils.net import find_free_ports

    class _Echo(Model):
        def load(self):
            self.ready = True

        def predict(self, payload, headers=None):
            return {"predictions": payload.get("instances", [])}

    class _Failing(Model):
        def load(self):
            self.ready = True

        def predict(self, payload, headers=None):
            raise RuntimeError("boom")

    api = APIServer()
    proxy = ServiceProxy(api)
    asc = ConcurrencyAutoscaler(api)
    rem = R.FleetRemediator(api=api, autoscaler=asc)
    proxy.attach_remediator(rem)
    srv_bad = ModelServer([_Failing("m")], port=0)
    srv_ok = ModelServer([_Echo("m")], port=0)
    srv_bad.start()
    srv_ok.start()
    svc_port = find_free_ports(1)[0]
    try:
        api.create(_service(extra_ann={
            PROXY_PORT_ANNOTATION: str(svc_port)}))
        api.create(_deployment(replicas=1))
        for i, port in enumerate((srv_bad.port, srv_ok.port)):
            api.create(_pod(f"svc-{i}", port=port))
        proxy.sync()
        rem.start()
        for i in range(6):  # RR hits the 500ing backend -> failovers
            req = urllib.request.Request(
                f"http://127.0.0.1:{svc_port}/v1/models/m:predict",
                data=json.dumps({"instances": [i]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.status == 200
        _wait(lambda: rem.status()["actions"], timeout=10.0,
              msg="remediation action")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{svc_port}/fleet/remediation",
                timeout=30) as resp:
            body = json.loads(resp.read())
        assert body["human_actions"] == 0
        acts = [a for a in body["actions"]
                if a["playbook"] == "replace_replica"]
        assert acts and acts[0]["outcome"] == "executed"
        assert body["proposals"]["d"]["floor"] == 2
        # the incident bundle carries the decision
        mgr = proxy._states[("default", "svc")].incidents
        open_incs = [i for i in mgr.list() if i["state"] == "open"]
        assert open_incs[0]["cause"] == "replica_death"
        assert open_incs[0]["remediation"]["status"] == "in_flight"
        # arbitration, live: the autoscaler (not the remediator) scales
        asc.sync()
        assert api.get("Deployment", "d")["spec"]["replicas"] == 2
    finally:
        rem.stop()
        proxy.shutdown()
        srv_bad.stop()
        srv_ok.stop()
