"""Incident-plane tests (README "Incident plane", serving/incidents.py).

Coverage per the ISSUE 13 satellite list:

  * detector firing + debounce coalescing — one incident per fault burst,
    not one per symptom, driven with an explicit clock for determinism;
  * the classification table — every chaos class the repo can inject maps
    to its expected root cause (faults.EXPECTED_INCIDENT_CAUSES is the
    contract) from the evidence SHAPE alone;
  * end-to-end engine incidents: watchdog death -> replica_death, storage
    bit-flip -> storage_degradation, bad handoff import ->
    handoff_degradation, mismatched fabric frame -> fabric_degradation,
    queue-overload -> capacity — each exactly ONE incident citing >= 1
    live trace id and a readable flight-recorder dump;
  * the false-positive gate: a clean 50-request run opens ZERO incidents;
  * postmortem bundle schema round-trip (atomic JSON on disk == the
    served incident);
  * fleet merge dedupe: two replicas reporting the same failover (same
    cause, overlapping trace ids) merge into one entry;
  * SLO burn-threshold config (unknown-class validation, snapshot as the
    one source of truth) and the TraceStore LRU satellite's engine-side
    consumer;
  * metric exposition: incidents_open / incidents_total{cause} /
    incident_detector_firings_total{detector};
  * autoscaler: flap events feed the manager, open incidents veto
    scale-down.
"""

import json
import os
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from kubeflow_tpu.serving import incidents as I
from kubeflow_tpu.serving.engine import Engine, EngineConfig
from kubeflow_tpu.serving.engine import model as M
from kubeflow_tpu.serving.engine.faults import (EXPECTED_INCIDENT_CAUSES,
                                                FaultConfig,
                                                StorageFaultConfig)
from kubeflow_tpu.serving.engine.kvstore import KVStoreConfig
from kubeflow_tpu.serving.errors import EngineOverloaded
from kubeflow_tpu.serving.slo import (DEFAULT_BURN_THRESHOLD, SloConfig,
                                      SloTracker)

pytestmark = pytest.mark.incident

CFG = M.DecoderConfig(vocab_size=101, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=128)

# Operator-sane SLO targets for a saturated 1-CPU test box: a closed-loop
# burst against the sub-second default interactive targets IS a real SLO
# burn (the detector firing there is correct behavior, not a false
# positive), so the cause-targeted tests pin generous targets and the
# burn test brings its own tight ones.
_GENEROUS_SLO = SloConfig(targets=tuple(
    (c, m, 600.0) for c in ("interactive", "batch", "best_effort")
    for m in ("ttft", "tpot", "queue_wait")))


@pytest.fixture(scope="module")
def params():
    return M.init(jax.random.PRNGKey(0), CFG)


def _ec(**kw):
    base = dict(max_slots=4, num_pages=128, page_size=8,
                max_pages_per_slot=16, slo=_GENEROUS_SLO,
                incidents=True, incident_debounce_s=0.5,
                incident_resolve_s=1.0, incident_poll_s=0.05)
    base.update(kw)
    return EngineConfig(**base)


def _wait(pred, timeout=30.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    raise TimeoutError(f"timed out waiting for {msg}")


def _wait_resolved(eng, n=1, timeout=30.0):
    """Wait until exactly ``n`` incidents exist and all are resolved —
    including the RESOLUTION REWRITE of their on-disk bundles: the
    manager flips state under its lock and rewrites the bundle after,
    so a reader racing that gap would diff an open bundle against a
    resolved incident (flaked under suite load)."""
    _wait(lambda: len(eng.incident_list()) >= n, timeout=timeout,
          msg=f"{n} incident(s)")
    _wait(lambda: all(i["state"] == "resolved"
                      for i in eng.incident_list()),
          timeout=timeout, msg="incident resolution")

    def bundles_current():
        for i in eng.incident_list():
            p = i.get("bundle_path")
            if not p or not os.path.exists(p):
                return False
            with open(p) as f:
                if json.load(f).get("state") != i["state"]:
                    return False
        return True

    _wait(bundles_current, timeout=timeout, msg="bundle rewrite")
    return eng.incident_list()


def _assert_bundle(inc):
    """Every incident must cite >=1 live trace id and a READABLE
    flight-recorder dump, and its on-disk bundle must round-trip to the
    served incident (the ISSUE 13 acceptance shape)."""
    assert inc["evidence"]["trace_ids"], inc
    dump = inc["evidence"]["flight_dump"]
    assert dump and os.path.exists(dump), inc
    with open(dump) as f:
        header = json.loads(f.readline())
    assert "reason" in header  # readable JSONL postmortem
    assert inc["bundle_path"] and os.path.exists(inc["bundle_path"])
    with open(inc["bundle_path"]) as f:
        disk = json.load(f)
    assert disk["id"] == inc["id"]
    assert disk["cause"] == inc["cause"]
    assert disk["state"] == inc["state"]
    assert disk["evidence"]["trace_ids"] == inc["evidence"]["trace_ids"]
    assert [s["kind"] for s in disk["symptoms"]] == \
        [s["kind"] for s in inc["symptoms"]]


# ------------------------------------------------- detector units + debounce


def test_debounce_coalesces_burst_into_one_incident():
    """A burst of symptoms inside the debounce window is ONE incident with
    a causal chain — not an alert storm; quiet resolves it."""
    m = I.IncidentManager("t", I.IncidentConfig(debounce_s=1.0,
                                               resolve_s=2.0),
                          detectors=I.engine_detectors())
    for i in range(6):
        m.feed("degradation", source="storage", outcome="corrupt",
               trace_ids=[f"tid{i}"])
    now = time.monotonic()
    m._process(now)
    incs = m.list()
    assert len(incs) == 1
    assert incs[0]["state"] == "open"
    assert len(incs[0]["symptoms"]) == 6
    assert incs[0]["cause"] == "storage_degradation"
    # all six trace ids accumulated as evidence
    assert incs[0]["evidence"]["trace_ids"] == [f"tid{i}"
                                                for i in range(6)]
    assert m.firings == 6  # firings counted per symptom, incidents once
    # quiet past resolve_s -> resolved with a resolution record
    m._process(now + 3.0)
    incs = m.list()
    assert incs[0]["state"] == "resolved"
    assert "no new symptoms" in incs[0]["resolution"]["reason"]


def test_burst_past_debounce_opens_distinct_incident():
    m = I.IncidentManager("t", I.IncidentConfig(debounce_s=0.5,
                                               resolve_s=10.0),
                          detectors=I.engine_detectors())
    m.feed("degradation", source="storage", outcome="corrupt",
           trace_ids=["a"])
    m._process(time.monotonic())
    time.sleep(0.6)  # past debounce: a NEW burst, not a cascade
    m.feed("degradation", source="fabric", outcome="hash_mismatch",
           trace_ids=["b"])
    m._process(time.monotonic())
    incs = m.list()
    assert len(incs) == 2
    assert {i["cause"] for i in incs} == {"storage_degradation",
                                          "fabric_degradation"}


def test_debounce_must_not_exceed_resolve():
    """A resolve window shorter than the debounce would close incidents
    mid-coalescing-window — the config refuses it up front (the Engine
    builds its IncidentConfig from the EngineConfig knobs, so a bad
    engine.json fails at construction with the same message)."""
    with pytest.raises(ValueError, match="must not exceed"):
        I.IncidentConfig(debounce_s=10.0, resolve_s=5.0)
    assert I.IncidentConfig(debounce_s=5.0, resolve_s=5.0)  # equal is fine


def test_unmatched_events_are_dropped_not_incidents():
    m = I.IncidentManager("t", detectors=I.ingress_detectors())
    m.feed("degradation", source="storage", outcome="x")  # engine-scope
    m._process(time.monotonic())
    assert m.list() == []
    assert m.stats()["events_dropped"] == 1


def test_reclassification_as_causal_chain_grows():
    """The first symptom may be a secondary effect: a tick overrun alone
    reads unknown, but a watchdog trip in the same window re-names the
    incident replica_death."""
    m = I.IncidentManager("t", I.IncidentConfig(debounce_s=5.0),
                          detectors=I.engine_detectors())
    m.feed("tick_overrun", duration_s=2.0, trace_ids=["t1"])
    m._process(time.monotonic())
    assert m.list()[0]["cause"] == "unknown"
    m.feed("watchdog", detail="loop thread died", trace_ids=["t1"])
    m._process(time.monotonic())
    incs = m.list()
    assert len(incs) == 1
    assert incs[0]["cause"] == "replica_death"


# --------------------------------------------------- the classification table


# evidence SHAPE each chaos class leaves, per the signal feed sites
_SHAPES = {
    # fleet chaos: the ingress sees failed relay attempts (+ breaker)
    "fleet:kill": [{"kind": "failover", "reason": "stream"},
                   {"kind": "breaker_open"}],
    "fleet:hang": [{"kind": "failover", "reason": "stall"}],
    "fleet:slow": [{"kind": "failover", "reason": "stall"},
                   {"kind": "failover", "reason": "stall"}],
    "fleet:cut": [{"kind": "failover", "reason": "stream",
                   "resume": True}],
    # engine chaos: the watchdog supervises the loop back to life
    "engine:die_on_tick": [{"kind": "watchdog",
                            "detail": "loop thread died"}],
    "engine:slow_tick": [{"kind": "watchdog",
                          "detail": "loop hung > 0.5s inside one tick"}],
    # storage chaos: session restores degrade to recompute
    "storage:torn_write": [{"kind": "degradation", "source": "storage",
                            "outcome": "corrupt"}],
    "storage:bit_flip": [{"kind": "degradation", "source": "storage",
                          "outcome": "corrupt"}],
    "storage:enospc": [{"kind": "degradation", "source": "storage",
                        "outcome": "restore_error"}],
    # handoff chaos: disagg imports degrade to re-prefill
    "handoff:torn_pull": [{"kind": "degradation", "source": "handoff",
                           "outcome": "pre_submit"}],
    "handoff:slow_pull": [{"kind": "degradation", "source": "handoff",
                           "outcome": "pre_submit"}],
    "handoff:dead_link": [{"kind": "degradation", "source": "handoff",
                           "outcome": "pre_submit"}],
    "handoff:expired_export": [{"kind": "degradation",
                                "source": "handoff",
                                "outcome": "pre_submit"}],
    # sharded-frame chaos: ONE corrupted sub-frame fails the per-shard
    # verifier and degrades exactly like a torn unified frame
    "handoff:shard_torn_pull": [{"kind": "degradation",
                                 "source": "handoff",
                                 "outcome": "pre_submit"}],
    "handoff:shard_flip_pull": [{"kind": "degradation",
                                 "source": "handoff",
                                 "outcome": "pre_submit"}],
    "handoff:shard_drop_pull": [{"kind": "degradation",
                                 "source": "handoff",
                                 "outcome": "pre_submit"}],
    # fabric chaos: prefix pulls degrade to plain re-prefill
    "fabric:torn_pull": [{"kind": "degradation", "source": "fabric",
                          "outcome": "pre_submit"}],
    "fabric:flip_pull": [{"kind": "degradation", "source": "fabric",
                          "outcome": "pre_submit"}],
    "fabric:slow_pull": [{"kind": "degradation", "source": "fabric",
                          "outcome": "pre_submit"}],
    "fabric:dead_link": [{"kind": "degradation", "source": "fabric",
                          "outcome": "pre_submit"}],
    "fabric:expired_publish": [{"kind": "degradation", "source": "fabric",
                                "outcome": "pre_submit"}],
    "fabric:shard_torn_pull": [{"kind": "degradation", "source": "fabric",
                                "outcome": "pre_submit"}],
    "fabric:shard_flip_pull": [{"kind": "degradation", "source": "fabric",
                                "outcome": "pre_submit"}],
    "fabric:shard_drop_pull": [{"kind": "degradation", "source": "fabric",
                                "outcome": "pre_submit"}],
    # traffic storm: the ingress overload controller's aggregated shed
    # bursts + brownout stage transitions (README "Overload control")
    "storm:overload": [{"kind": "shed", "reason": "concurrency",
                        "shed": 5, "stage": 1},
                       {"kind": "brownout", "stage": 2, "from_stage": 1}],
    # constrain chaos: a forced-empty mask row stalls the automaton —
    # classified over any shed storm it drags behind it (a code bug,
    # never load; README "Structured output")
    "constrain:stall": [{"kind": "constraint_stall",
                         "error": "zero legal tokens under grammar mask"}],
}


def test_classification_table_covers_every_chaos_class():
    """faults.EXPECTED_INCIDENT_CAUSES is the contract: every chaos class
    the repo can inject has an evidence shape here, and classify() names
    the expected cause for each."""
    assert set(_SHAPES) == set(EXPECTED_INCIDENT_CAUSES)
    for chaos_class, symptoms in _SHAPES.items():
        cause, rule = I.classify(symptoms)
        assert cause == EXPECTED_INCIDENT_CAUSES[chaos_class], \
            (chaos_class, cause, rule)
        assert cause in I.CAUSES


def test_classify_prefill_interference_needs_both_signals():
    """Sarathi-Serve's signature: decode TPOT burn + live prefill backlog.
    Either alone is NOT interference (a lone tpot burn is unknown, queue
    pressure alone is capacity)."""
    both = [{"kind": "slo_burn", "metric": "tpot", "prefill_active": 3}]
    assert I.classify(both)[0] == "prefill_interference"
    burn_only = [{"kind": "slo_burn", "metric": "tpot",
                  "prefill_active": 0}]
    assert I.classify(burn_only)[0] == "unknown"
    ttft_burn = [{"kind": "slo_burn", "metric": "ttft",
                  "prefill_active": 3}]
    assert I.classify(ttft_burn)[0] == "unknown"
    queue_only = [{"kind": "queue_growth", "queue_depth": 9}]
    assert I.classify(queue_only)[0] == "capacity"


def test_classify_precedence_and_fallback():
    # replica death outranks the secondary symptoms it drags behind it
    mixed = [{"kind": "slo_burn", "metric": "tpot", "prefill_active": 2},
             {"kind": "watchdog", "detail": "died"},
             {"kind": "degradation", "source": "storage"}]
    assert I.classify(mixed)[0] == "replica_death"
    # flap with healthy replicas is a capacity-control fault
    assert I.classify([{"kind": "flap", "flips": 3}])[0] == "capacity"
    # the honest fallback
    assert I.classify([{"kind": "nan_guard"}])[0] == "unknown"
    # dominant degradation source wins over a stray secondary one
    storm = [{"kind": "degradation", "source": "fabric"}] * 3 \
        + [{"kind": "degradation", "source": "storage"}]
    assert I.classify(storm)[0] == "fabric_degradation"


# --------------------------------------------------------- end-to-end engine


def test_e2e_watchdog_death_is_one_replica_death_incident(params,
                                                          tmp_path):
    eng = Engine(params, CFG, _ec(
        incident_dir=str(tmp_path / "bundles"),
        watchdog_interval_s=0.1, hang_timeout_s=0.5,
        chaos=FaultConfig(seed=0, die_on_tick=3)))
    eng.start()
    try:
        with pytest.raises(Exception):
            eng.generate([1, 2, 3, 4], 8, timeout=60)
        incs = _wait_resolved(eng)
        assert len(incs) == 1
        inc = incs[0]
        assert inc["cause"] == "replica_death"
        assert inc["detector"] == "watchdog"
        _assert_bundle(inc)
        assert str(tmp_path / "bundles") in inc["bundle_path"]
    finally:
        eng.stop()


def test_e2e_storage_bit_flip_is_one_storage_incident(params, tmp_path):
    """A session pinned to a bit-flipping disk tier restores degraded;
    the incident plane names storage_degradation from that outcome."""
    eng = Engine(params, CFG, _ec(
        kv_store=KVStoreConfig(
            host_max_bytes=0,  # force every pin through the disk tier
            disk_dir=str(tmp_path / "kv"),
            chaos=StorageFaultConfig(seed=0, bit_flip_every=1))))
    eng.start()
    try:
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]
        r1 = eng.generate(prompt, 12, session_id="s1", timeout=120)
        assert r1["session"]["pinned"]
        # turn 2 extends turn 1's context (prompt + generated)
        r2 = eng.generate(prompt + r1["tokens"], 8, session_id="s1",
                          timeout=120)
        assert r2["session"]["restore"] == "degraded"
        incs = _wait_resolved(eng)
        assert len(incs) == 1
        assert incs[0]["cause"] == "storage_degradation"
        assert incs[0]["detector"] == "storage_degradation"
        _assert_bundle(incs[0])
    finally:
        eng.stop()


def test_e2e_bad_handoff_import_is_one_handoff_incident(params):
    """A kv_import whose resume_len disagrees with the prompt degrades at
    submit (the engine-side backstop) — and the request still completes."""
    eng = Engine(params, CFG, _ec())
    eng.start()
    try:
        r = eng.generate([1, 2, 3, 4], 6, timeout=120,
                         kv_import=(b"bogus", 5, 99))  # resume_len != 4
        assert len(r["tokens"]) > 0  # degraded, never failed
        incs = _wait_resolved(eng)
        assert len(incs) == 1
        assert incs[0]["cause"] == "handoff_degradation"
        _assert_bundle(incs[0])
    finally:
        eng.stop()


def test_e2e_mismatched_fabric_frame_is_one_fabric_incident(params):
    """A fabric frame sharing no chain hash with the prompt degrades at
    admission (hash_mismatch) — the wrong-placement cost the fabric's
    degradation contract pays."""
    eng = Engine(params, CFG, _ec())
    eng.start()
    try:
        prompt = list(range(1, 19))  # 18 tokens = 2 full pages + tail
        bogus = np.asarray([7, 9], np.uint64)  # matches nothing
        r = eng.generate(prompt, 6, timeout=120,
                         fabric_import=(("k", "v"), bogus, 128))
        assert r["fabric"]["restore"] == "degraded"
        assert len(r["tokens"]) > 0
        incs = _wait_resolved(eng)
        assert len(incs) == 1
        assert incs[0]["cause"] == "fabric_degradation"
        _assert_bundle(incs[0])
    finally:
        eng.stop()


def test_e2e_overload_rejections_are_one_capacity_incident(params):
    eng = Engine(params, CFG, _ec(max_queue_depth=1))
    # submit BEFORE start: nothing admits, so the queue-depth bound is
    # deterministically hit — the first submit fills the queue, every
    # later one is an EngineOverloaded rejection feeding the plane
    fut = eng.generate_async([1, 2, 3, 4], 8)
    rejected = 0
    for _ in range(5):
        try:
            eng.generate_async([5, 6, 7, 8], 8)
        except EngineOverloaded:
            rejected += 1
    assert rejected == 5
    eng.start()
    try:
        fut.result(timeout=120)
        incs = _wait_resolved(eng)
        assert len(incs) == 1
        assert incs[0]["cause"] == "capacity"
        assert incs[0]["detector"] == "admission_pressure"
        # a rejection storm is one incident with one symptom per rejection
        assert len(incs[0]["symptoms"]) == rejected
        assert incs[0]["symptoms"][0]["queue_depth"] >= 1
        _assert_bundle(incs[0])
    finally:
        eng.stop()


def test_false_positive_gate_clean_run_zero_incidents(params):
    """The ISSUE 13 acceptance gate: a clean 50-request run with the
    incident plane ON (and a tick-overrun budget armed) opens ZERO
    incidents — no detector may fire from the machinery itself (the
    SLO targets are sized for the hardware; a burst against sub-second
    targets on a CPU box would be a REAL burn, not a false positive)."""
    eng = Engine(params, CFG, _ec(max_slots=8,
                                  incident_tick_overrun_s=30.0))
    eng.start()
    try:
        futs = [eng.generate_async(
            [(i * 13 + j * 7) % (CFG.vocab_size - 1) + 1
             for j in range(4 + i % 3)], 6) for i in range(50)]
        for f in futs:
            f.result(timeout=300)
        time.sleep(0.3)  # a full poll cycle: burn detector gets its look
        assert eng.incident_list() == []
        assert eng.stats["incidents"]["firings"] == 0
    finally:
        eng.stop()
    # post-stop: the final manager pass ran; still nothing
    assert eng.incident_list() == []


# --------------------------------------------------------------- fleet merge


def test_fleet_merge_dedupes_same_failover_across_replicas():
    """Two replicas reporting the same fault — same cause, overlapping
    trace ids — merge into ONE fleet entry listing both origins; an
    unrelated incident stays distinct even with the same cause."""
    a = {"id": "inc-a", "cause": "replica_death", "state": "resolved",
         "opened_wall": 10.0, "evidence": {"trace_ids": ["t1", "t2"]}}
    b = {"id": "inc-b", "cause": "replica_death", "state": "open",
         "opened_wall": 10.5, "evidence": {"trace_ids": ["t2"]}}
    c = {"id": "inc-c", "cause": "replica_death", "state": "resolved",
         "opened_wall": 11.0, "evidence": {"trace_ids": ["t9"]}}
    d = {"id": "inc-d", "cause": "capacity", "state": "resolved",
         "opened_wall": 12.0, "evidence": {"trace_ids": ["t1"]}}
    merged = I.merge_fleet_incidents(
        [("replica-0", a), ("replica-1", b), ("replica-1", c),
         ("ingress", d)])
    assert len(merged) == 3
    dup = next(m for m in merged if "inc-a" in m["merged_ids"])
    assert sorted(dup["origins"]) == ["replica-0", "replica-1"]
    assert sorted(dup["merged_ids"]) == ["inc-a", "inc-b"]
    assert set(dup["evidence"]["trace_ids"]) == {"t1", "t2"}
    assert dup["state"] == "open"  # any open origin keeps it open
    # same cause, disjoint trace evidence: NOT merged
    assert any(m["merged_ids"] == ["inc-c"] for m in merged)
    # same trace id, different cause: NOT merged
    assert any(m["merged_ids"] == ["inc-d"] for m in merged)


# ----------------------------------------------------- SLO burn config + LRU


def test_slo_burn_threshold_config_and_snapshot():
    cfg = SloConfig.from_json({
        "burn_threshold": {"interactive": 4.0},
        "burn_window": {"interactive": 600}})
    t = SloTracker(cfg)
    assert t.burn_threshold("interactive") == 4.0
    assert t.burn_window("interactive") == 600.0
    # unconfigured classes: default threshold over the SHORTEST window
    assert t.burn_threshold("batch") == DEFAULT_BURN_THRESHOLD
    assert t.burn_window("batch") == 60.0
    # snapshot is the one source of truth the detector AND the evidence
    # view read: thresholds/windows surface next to the burn values
    t.observe("interactive", "ttft", 5.0, now=100.0)  # misses 1.0 target
    snap = t.snapshot(now=100.1)
    rec = snap["interactive"]["ttft"]
    assert rec["burn_threshold"] == 4.0
    assert rec["burn_window"] == "600s"
    assert rec["burn"]["600s"] > 4.0  # 100% miss rate >> threshold


def test_slo_burn_config_validation():
    with pytest.raises(ValueError, match="unknown burn_threshold"):
        SloConfig.from_json({"burn_threshold": {"interactiv": 4.0}})
    with pytest.raises(ValueError, match="unknown burn_window"):
        SloConfig.from_json({"burn_window": {"nope": 60}})
    with pytest.raises(ValueError, match="must be > 0"):
        SloConfig.from_json({"burn_threshold": {"batch": 0}})
    with pytest.raises(ValueError, match="not one of"):
        SloConfig.from_json({"burn_window": {"batch": 42.0}})


def test_e2e_burn_detector_reads_tracker_snapshot(params):
    """An impossible TPOT target burns immediately; the slo_burn detector
    fires from the tracker's own snapshot and the evidence carries the
    burn series.  With prefill backlog absent this classifies unknown —
    the interference discriminator is prefill evidence, not burn alone."""
    slo = SloConfig.from_json({
        "targets": {"interactive": {"tpot": 0.000001}},
        "windows": [60], "burn_threshold": {"interactive": 2.0},
        "burn_min_samples": 5})
    eng = Engine(params, CFG, _ec(slo=slo))
    eng.start()
    try:
        eng.generate([1, 2, 3, 4], 12, timeout=120)
        incs = _wait_resolved(eng)
        assert len(incs) == 1
        inc = incs[0]
        assert inc["detector"] == "slo_burn"
        s0 = inc["symptoms"][0]
        assert s0["metric"] == "tpot"
        assert s0["burn"] >= 2.0
        assert s0["threshold"] == 2.0
        # evidence cites resolvable traces even when the offending burst
        # already drained (the archived-span fallback)
        assert inc["evidence"]["trace_ids"]
        assert "slo" in inc["evidence"]  # the burn series as evidence
    finally:
        eng.stop()


def test_burn_detector_rearms_after_quiet_drain(params):
    """The edge-trigger latch must re-arm when a series cools off OR
    drains out of the snapshot entirely — otherwise the first burn of an
    engine's lifetime would be the only one ever detected."""
    eng = Engine(params, CFG, _ec())
    try:
        burning = {"interactive": {"tpot": {
            "burn_threshold": 2.0, "burn_window": "60s",
            "burn_samples": 50, "burn_min_samples": 10,
            "burn": {"60s": 30.0}}}}

        class _Slo:
            snap = burning

            def snapshot(self):
                return self.snap

        eng.telemetry.slo = _Slo()
        eng._incident_poll()
        assert eng.incidents.stats()["events_seen"] == 0  # queued only
        eng.incidents._process(time.monotonic())
        assert eng.incidents.stats()["events_seen"] == 1
        eng._incident_poll()  # still burning: edge-triggered, no repeat
        eng.incidents._process(time.monotonic())
        assert eng.incidents.stats()["events_seen"] == 1
        _Slo.snap = {}        # all samples aged out: series vanishes
        eng._incident_poll()
        assert not eng._burn_above  # latch re-armed
        _Slo.snap = burning   # episode 2 must fire again
        eng._incident_poll()
        eng.incidents._process(time.monotonic())
        assert eng.incidents.stats()["events_seen"] == 2
    finally:
        eng.stop()


def test_manager_reads_are_isolated_from_fleet_merge():
    """list()/get() hand out deep copies: the fleet merge mutates merged
    entries' evidence while deduping, and that must never write through
    to the manager's live incident."""
    m = I.IncidentManager("t", detectors=I.engine_detectors())
    m.feed("watchdog", detail="died", trace_ids=["t1"])
    m._process(time.monotonic())
    foreign = {"id": "inc-x", "cause": "replica_death",
               "state": "resolved", "opened_wall": 1e12,
               "evidence": {"trace_ids": ["t1", "t-foreign"]}}
    merged = I.merge_fleet_incidents(
        [("ingress", m.list()[0]), ("replica-1", foreign)])
    assert len(merged) == 1
    assert "t-foreign" in merged[0]["evidence"]["trace_ids"]
    # the live incident saw none of the merge's writes
    assert m.list()[0]["evidence"]["trace_ids"] == ["t1"]


def test_burn_detector_respects_min_samples(params):
    """One cold-compile miss out of a handful of requests must NOT page:
    below burn_min_samples the detector stays quiet even at burn 100."""
    slo = SloConfig.from_json({
        "targets": {"interactive": {"tpot": 0.000001}},
        "windows": [60], "burn_threshold": {"interactive": 2.0},
        "burn_min_samples": 500})
    eng = Engine(params, CFG, _ec(slo=slo))
    eng.start()
    try:
        eng.generate([1, 2, 3, 4], 12, timeout=120)
        time.sleep(0.3)  # several poll cycles
        assert eng.incident_list() == []
    finally:
        eng.stop()


# ------------------------------------------------------- HTTP + metrics


def test_engine_incidents_http_and_metrics(params):
    """GET /engine/incidents (list + timeline view) and the three new
    metric series, via a real ModelServer."""
    from kubeflow_tpu.serving.engine.serve import JetStreamModel
    from kubeflow_tpu.serving.server import ModelServer

    eng = Engine(params, CFG, _ec(
        watchdog_interval_s=0.1, hang_timeout_s=0.5,
        chaos=FaultConfig(seed=0, die_on_tick=3)))
    m = JetStreamModel("llm", engine=eng)
    server = ModelServer([m], port=0)
    server.start()
    try:
        eng.start()
        with pytest.raises(Exception):
            eng.generate([1, 2, 3, 4], 8, timeout=60)
        _wait_resolved(eng)
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/engine/incidents",
                                    timeout=30) as r:
            body = json.loads(r.read())
        assert body["open"] == 0
        assert len(body["incidents"]) == 1
        inc = body["incidents"][0]
        assert inc["cause"] == "replica_death"
        assert inc["model"] == "llm"
        with urllib.request.urlopen(
                base + f"/engine/incidents/{inc['id']}", timeout=30) as r:
            one = json.loads(r.read())
        steps = [row["step"] for row in one["timeline"]]
        # the responder's timeline: firing -> evidence -> classification
        # -> resolution, in that order
        assert steps[0] == "detector_fired"
        assert "evidence" in steps and "classified" in steps
        assert steps[-1] == "resolved"
        assert steps.index("evidence") < steps.index("classified")
        try:
            urllib.request.urlopen(base + "/engine/incidents/inc-nope",
                                   timeout=30)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            text = r.read().decode()
        assert 'incidents_total{cause="replica_death"' in text
        assert 'incident_detector_firings_total{detector="watchdog"' \
            in text
        assert "incidents_open" in text
    finally:
        server.stop()
        eng.stop()


def test_fleet_incidents_endpoint_over_failover(monkeypatch):
    """End to end through the real service proxy: a 500ing backend drives
    failover retries; the ingress incident manager coalesces them into
    ONE replica_death incident served (with its timeline) on
    GET /fleet/incidents and /fleet/incidents/<id>."""
    from kubeflow_tpu.core.api import APIServer
    from kubeflow_tpu.serving.controllers import (POD_PORT_ANNOTATION,
                                                  PROXY_PORT_ANNOTATION)
    from kubeflow_tpu.serving.api import LABEL_ISVC
    from kubeflow_tpu.serving.router import ServiceProxy
    from kubeflow_tpu.serving.server import Model, ModelServer
    from kubeflow_tpu.utils.net import find_free_ports

    class _Echo(Model):
        def load(self):
            self.ready = True

        def predict(self, payload, headers=None):
            return {"predictions": payload.get("instances", [])}

    class _Failing(Model):
        def load(self):
            self.ready = True

        def predict(self, payload, headers=None):
            raise RuntimeError("boom")

    api = APIServer()
    proxy = ServiceProxy(api)
    srv_bad = ModelServer([_Failing("m")], port=0)
    srv_ok = ModelServer([_Echo("m")], port=0)
    srv_bad.start()
    srv_ok.start()
    svc_port = find_free_ports(1)[0]
    try:
        api.create({
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": "svc", "labels": {LABEL_ISVC: "svc"},
                         "annotations": {
                             PROXY_PORT_ANNOTATION: str(svc_port)}},
            "spec": {"selector": {"app": "svc"}}})
        for i, port in enumerate((srv_bad.port, srv_ok.port)):
            api.create({
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": f"svc-{i}", "labels": {"app": "svc"},
                             "annotations": {
                                 POD_PORT_ANNOTATION: str(port)}},
                "spec": {},
                "status": {"phase": "Running",
                           "conditions": [{"type": "Ready",
                                           "status": "True"}]}})
        proxy.sync()
        for i in range(6):  # RR hits the 500ing backend -> retries
            req = urllib.request.Request(
                f"http://127.0.0.1:{svc_port}/v1/models/m:predict",
                data=json.dumps({"instances": [i]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.status == 200

        def fleet_incidents():
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{svc_port}/fleet/incidents",
                    timeout=30) as r:
                return json.loads(r.read())

        _wait(lambda: fleet_incidents()["incidents"], timeout=10.0,
              msg="ingress incident")
        body = fleet_incidents()
        # every failover strike + the breaker open coalesced into ONE
        assert len(body["incidents"]) == 1
        inc = body["incidents"][0]
        assert inc["cause"] == "replica_death"
        assert inc["origins"] == ["ingress"]
        assert inc["evidence"]["trace_ids"]  # the relayed trace ids
        kinds = {s["kind"] for s in inc["symptoms"]}
        assert "failover" in kinds
        with urllib.request.urlopen(
                f"http://127.0.0.1:{svc_port}/fleet/incidents/"
                f"{inc['id']}", timeout=30) as r:
            one = json.loads(r.read())
        assert one["incident"]["id"] == inc["id"]
        assert [row["step"] for row in one["timeline"]][0] \
            == "detector_fired"
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{svc_port}/fleet/incidents/inc-nope",
                timeout=30)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        proxy.shutdown()
        srv_bad.stop()
        srv_ok.stop()


# ----------------------------------------------------------- autoscaler ties


def test_autoscaler_flap_feeds_incident_and_classifies_capacity():
    from kubeflow_tpu.serving.autoscaler import ConcurrencyAutoscaler

    mgr = I.IncidentManager("ingress:t", I.IncidentConfig(debounce_s=5.0),
                            detectors=I.ingress_detectors())
    a = ConcurrencyAutoscaler.__new__(ConcurrencyAutoscaler)
    a.incidents = mgr
    a._scale_dirs = {}
    a._flap_fired = {}
    for d in (1, -1, 1, -1):
        a._note_scale("uid1", "dep", d)
    mgr._process(time.monotonic())
    incs = mgr.list()
    assert len(incs) == 1  # edge-triggered: one flap incident per window
    assert incs[0]["cause"] == "capacity"
    assert incs[0]["detector"] == "autoscaler_flap"
    assert incs[0]["symptoms"][0]["deployment"] == "dep"


def test_autoscaler_open_incident_vetoes_scale_down(monkeypatch):
    from kubeflow_tpu.core.api import APIServer
    from kubeflow_tpu.serving import autoscaler as asc
    from kubeflow_tpu.serving.api import TARGET_CONCURRENCY_ANNOTATION

    class _Mgr:
        n = 1

        def open_count(self):
            return self.n

        def feed(self, *a, **k):
            pass

    api = APIServer()
    mgr = _Mgr()
    a = asc.ConcurrencyAutoscaler(api, incidents=mgr)
    monkeypatch.setattr(asc, "SCALE_DOWN_WINDOW", 0.0)
    api.create({
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "d",
                     "annotations": {TARGET_CONCURRENCY_ANNOTATION: "4"}},
        "spec": {"replicas": 3,
                 "selector": {"matchLabels": {"app": "d"}},
                 "template": {"metadata": {"labels": {"app": "d"}},
                              "spec": {"containers": [
                                  {"name": "c", "command": ["x"]}]}}}})
    # zero load (no pods, no scrapes) -> desired collapses to the floor,
    # but the OPEN incident vetoes every shrink
    for _ in range(3):
        assert not a.sync()
    assert api.get("Deployment", "d")["spec"]["replicas"] == 3
    # incident resolves -> the normal damped downscale path resumes
    mgr.n = 0
    a.sync()                 # arms the (zeroed) stability window
    assert a.sync()          # shrink goes through now
    assert api.get("Deployment", "d")["spec"]["replicas"] == 1
