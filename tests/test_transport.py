"""C++ ring-collective transport shim (SURVEY.md §2b NCCL row).

Spawns real processes (the gang's shape) and checks collective numerics
against numpy; sanitizer builds are exercised by `make asan/tsan` in
kubeflow_tpu/transport/ (see test_sanitizer_builds).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import subprocess
import sys

import numpy as np
import pytest

from kubeflow_tpu.transport import RingTransport

BASE_PORT = 24800


def _worker(rank: int, world: int, port: int, q: mp.Queue) -> None:
    try:
        with RingTransport(rank, world, base_port=port) as tr:
            rng = np.random.default_rng(rank)
            x = rng.standard_normal(1000).astype(np.float32)
            expect = np.sum(
                [np.random.default_rng(r).standard_normal(1000).astype(np.float32)
                 for r in range(world)],
                axis=0,
            )
            got = tr.allreduce(x.copy())
            np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)

            rs = tr.reduce_scatter(x.copy())
            base, rem = divmod(1000, world)
            mine = (rank + 1) % world
            lo = mine * base + min(mine, rem)
            ln = base + (1 if mine < rem else 0)
            np.testing.assert_allclose(rs, expect[lo:lo + ln], rtol=1e-5, atol=1e-5)

            ag = tr.allgather(np.array([rank, rank * 2], np.int64))
            np.testing.assert_array_equal(
                ag, np.array([[r, r * 2] for r in range(world)], np.int64)
            )

            b = tr.broadcast(
                np.full(17, rank, np.float32) if rank == 1 else np.zeros(17, np.float32),
                root=1,
            )
            np.testing.assert_array_equal(b, np.full(17, 1, np.float32))
            tr.barrier()
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - failure path
        q.put((rank, f"{type(e).__name__}: {e}"))


@pytest.mark.parametrize("world", [
    2,
    pytest.param(4, marks=pytest.mark.slow),
    pytest.param(3, marks=pytest.mark.slow),
])
def test_ring_collectives_multiprocess(world):
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = BASE_PORT + world * 10
    procs = [ctx.Process(target=_worker, args=(r, world, port, q)) for r in range(world)]
    for p in procs:
        p.start()
    results = [q.get(timeout=60) for _ in range(world)]
    for p in procs:
        p.join(timeout=30)
    assert all(msg == "ok" for _, msg in results), results


def test_world_one_identity():
    with RingTransport(0, 1) as tr:
        x = np.arange(5, dtype=np.float32)
        np.testing.assert_array_equal(tr.allreduce(x.copy()), x)
        np.testing.assert_array_equal(tr.reduce_scatter(x.copy()), x)
        tr.barrier()


def worker_uneven(rank: int, world: int, port: int, q) -> None:
    try:
        with RingTransport(rank, world, base_port=port) as tr:
            x = np.full(7, float(rank + 1), np.float32)
            got = tr.allreduce(x)
            np.testing.assert_allclose(got, np.full(7, 6.0, np.float32))
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover
        q.put((rank, repr(e)))


def test_uneven_sizes():
    """n not divisible by world exercises the remainder chunk paths."""
    world = 3
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=worker_uneven, args=(r, world, BASE_PORT + 500, q))
             for r in range(world)]
    for p in procs:
        p.start()
    results = [q.get(timeout=60) for _ in range(world)]
    for p in procs:
        p.join(timeout=30)
    assert all(msg == "ok" for _, msg in results), results


def test_grad_allreduce_pytree():
    """grad_allreduce flattens a pytree into one bucket and averages."""
    from kubeflow_tpu.transport import grad_allreduce

    with RingTransport(0, 1) as tr:
        tree = {"a": np.ones((2, 3), np.float32), "b": [np.full(4, 2.0, np.float32)]}
        out = grad_allreduce(tr, tree)
        np.testing.assert_array_equal(out["a"], tree["a"])
        np.testing.assert_array_equal(out["b"][0], tree["b"][0])


@pytest.mark.slow
def test_resnet_ddp_through_shim_matches_single_process(tmp_path):
    """VERDICT r1 item 2 'done' bar: 4-process ResNet DDP through the
    PyTorchJob reconcile path with gradient sync via the C++ shim; final
    loss matches a single-process run on the same global batch."""
    from kubeflow_tpu.core.cluster import Cluster
    from kubeflow_tpu.training import api as tapi
    from kubeflow_tpu.training.api import ReplicaSpec, job
    from kubeflow_tpu.training.client import TrainingClient
    from kubeflow_tpu.training.frameworks import install

    wenv = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": "/root/repo",
        "DDP_TRANSPORT": "shim",
        "TRAIN_STEPS": "2",
        "PER_CHIP_BATCH": "2",
        "IMAGE_SIZE": "16",
    }
    cmd = [sys.executable, "-u", "-m", "kubeflow_tpu.examples.resnet_ddp_worker"]
    c = Cluster(cpu_nodes=1)
    install(c.api, c.manager)
    try:
        spec = job(
            "PyTorchJob",
            "resnet-ddp-shim",
            {
                "Master": ReplicaSpec(replicas=1, command=cmd, env=dict(wenv)),
                "Worker": ReplicaSpec(replicas=3, command=cmd, env=dict(wenv)),
            },
        )
        client = TrainingClient(c)
        client.create_job(spec)
        assert client.wait_for_job("PyTorchJob", "resnet-ddp-shim", timeout=600) == tapi.SUCCEEDED
        logs = "\n".join(client.get_job_logs("PyTorchJob", "resnet-ddp-shim").values())
        assert "transport=shim" in logs
        assert "RESNET-DDP-OK" in logs
        shim_losses = {
            float(line.split("=")[1]) for line in logs.splitlines() if line.startswith("loss=")
        }
        assert len(shim_losses) == 1, f"ranks disagree: {shim_losses}"
    finally:
        c.shutdown()

    # single-process reference on the SAME global batch (4 ranks × 2 = batch 8)
    env = dict(os.environ, JAX_PLATFORMS="cpu", DDP_TRANSPORT="shim", RANK="0",
               WORLD_SIZE="1", TRAIN_STEPS="2", PER_CHIP_BATCH="8", IMAGE_SIZE="16",
               PYTHONPATH="/root/repo")
    out = subprocess.run(
        [sys.executable, "-u", "-m", "kubeflow_tpu.examples.resnet_ddp_worker"],
        env=env, capture_output=True, text=True, timeout=400,
    )
    assert "RESNET-DDP-OK" in out.stdout, out.stderr[-2000:]
    ref_loss = next(
        float(line.split("=")[1]) for line in out.stdout.splitlines() if line.startswith("loss=")
    )
    # tolerance: batch-norm uses LOCAL batch statistics per rank (batch 2 here
    # vs 8 in the reference run) — faithful torch-DDP semantics, small drift
    assert abs(ref_loss - shim_losses.pop()) < 5e-2, (ref_loss, shim_losses)


@pytest.mark.slow
def test_sanitizer_builds():
    """SURVEY.md §5: the C++ core must build under ASAN and TSAN."""
    d = os.path.join(os.path.dirname(__file__), "..", "kubeflow_tpu", "transport")
    for target in ("asan", "tsan"):
        subprocess.run(["make", target], cwd=d, check=True, capture_output=True)
    subprocess.run(["make", "clean"], cwd=d, check=True, capture_output=True)


@pytest.mark.slow  # fast lane must stay under its 5-min budget (r1 #10)
def test_transport_bench_harness_measures_a_world():
    """The shim microbench (VERDICT r3 #7) produces rows with sane
    latency/bandwidth numbers for one small world."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "transport_bench",
        os.path.join(os.path.dirname(os.path.dirname(__file__)),
                     "benchmarks", "transport_bench.py"))
    tb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tb)
    rec = tb.run_world(4, [4096], iters=3, port=26110)
    assert rec is not None and rec["world"] == 4
    assert [r["bytes"] for r in rec["rows"]] == [4096]
    for r in rec["rows"]:
        assert r["p50_ms"] > 0 and r["busbw_MBps"] > 0
