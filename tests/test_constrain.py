"""Structured output tests (ISSUE 19, serving/constrain.py + the engine's
fused mask path): grammar-constrained decoding as a static-shape boolean
mask over the vocabulary.

The contract under test, layer by layer:

  * the byte-level pushdown automaton — hand-built EBNF grammars,
    JSON-Schema compilation, ``token_mask`` correctness against the
    brute-force legal-token oracle (mask[t] == "advance(t) succeeds on a
    clone"), O(1) clone independence, and byte-exact snapshot/restore
    with CRC guards;
  * the registry — token maps built once per vocab, disk-cached with a
    payload CRC, and a corrupted cache degrading to a COUNTED re-compile
    that is byte-identical to a cold build (never an invalid map);
  * the engine — the byte-identity oracle (constrained output identical
    to unconstrained whenever the unconstrained output complies, and
    grammar-valid always) across pipeline depth {0,1} x speculation
    {off,on}, closed-grammar graceful finish without an eos id, eos
    composition, automaton snapshots riding preempt/resume like KV,
    brownout stage 2 dropping drafts but NEVER the mask, and the seeded
    constrain chaos classes (forced zero-legal-token masks fail ONLY the
    victim with ConstraintStall + a constraint_stall incident; every
    surviving output stays grammar-valid — 0 invalid outputs);
  * the serve/ingress surface — schema validated at admission (400 with
    the compiler's message), structured ``json``/``tool_call`` response
    fields and SSE events, the OpenAI response_format/tool_choice
    mapping, and the two new metrics' exposition;
  * the cross-module pins — brownout never degrades the mask
    (overload.BROWNOUT_NEVER_DEGRADES) and the chaos -> cause ->
    playbook taxonomy rows for constraint_stall.
"""

import json
import re as re_mod

import jax
import numpy as np
import pytest

from kubeflow_tpu.serving import overload
from kubeflow_tpu.serving.constrain import (ConstrainRegistry,
                                            ConstraintStall,
                                            GrammarConstraint, GrammarError,
                                            TokenTable, compile_grammar,
                                            compile_json_schema, compile_spec,
                                            json_grammar)
from kubeflow_tpu.serving.engine import Engine, EngineConfig
from kubeflow_tpu.serving.engine import model as M
from kubeflow_tpu.serving.engine.faults import (EXPECTED_INCIDENT_CAUSES,
                                                ConstrainChaos,
                                                ConstrainFaultConfig,
                                                FaultConfig)
from kubeflow_tpu.serving.engine.serve import ByteTokenizer, JetStreamModel
from kubeflow_tpu.serving.errors import RequestError
from kubeflow_tpu.serving.incidents import CAUSES
from kubeflow_tpu.serving.remediator import CAUSE_PLAYBOOK
from kubeflow_tpu.serving.server import openai_constrain_spec

pytestmark = pytest.mark.constrain

CFG = M.DecoderConfig(vocab_size=101, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=128)
# small vocab => prompt-lookup drafts genuinely get accepted (the
# test_spec_pipeline rationale), which the spec-composition tests need
CFG_ACC = M.DecoderConfig(vocab_size=13, d_model=64, n_layers=2, n_heads=4,
                          n_kv_heads=2, d_ff=128)


@pytest.fixture(scope="module")
def params():
    return M.init(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def params_acc():
    return M.init(jax.random.PRNGKey(0), CFG_ACC)


# one-byte token tables matching the test model vocabs: token id i <-> the
# single byte i, so grammars talk about bytes and tests talk about tokens
TABLE101 = TokenTable([bytes([i]) for i in range(101)])
TABLE13 = TokenTable([bytes([i]) for i in range(13)])
TABLE256 = TokenTable([bytes([i]) for i in range(256)])

# every token legal forever: the identity-oracle grammar (\x64 == 100)
ALL_LEGAL_101 = r"start ::= [\x00-\x64]* ;"
ALL_LEGAL_13 = r"start ::= [\x00-\x0c]* ;"
AB_C = 'start ::= "ab" ("ab")* "c" ;'  # bytes 97/98 then a closing 99

ALL_VOCAB = list(range(1, CFG.vocab_size))
PROMPTS = [ALL_VOCAB, [7, 3, 9, 5] * 6,
           [(i * 13 + 7) % (CFG.vocab_size - 1) + 1 for i in range(9)]]


def _con(text: str, table=TABLE101) -> GrammarConstraint:
    return GrammarConstraint(compile_grammar(text), table)


def _walk(grammar, data: bytes):
    """Feed bytes one at a time through a byte table; returns the
    constraint after the last byte that advanced, plus success."""
    c = GrammarConstraint(grammar, TABLE256)
    for b in data:
        if not c.advance(b):
            return c, False
    return c, True


def _accepts(grammar, data: bytes) -> bool:
    c, ok = _walk(grammar, data)
    return ok and c.accepting()


def _assert_mask_matches_oracle(c: GrammarConstraint):
    """The core mask contract: mask[t] is True exactly when advancing a
    CLONE by token t succeeds."""
    mask = c.token_mask()
    for tid in range(c.table.vocab_size):
        assert bool(mask[tid]) == c.clone().advance(tid), (
            f"mask[{tid}]={bool(mask[tid])} disagrees with advance()")


# ------------------------------------------------------- automaton units


def test_literal_grammar_walk_and_masks():
    c = _con(AB_C)
    assert not c.accepting()
    m0 = c.token_mask()
    assert m0[97] and not m0[98] and not m0[99]  # only 'a' opens
    assert c.advance(97) and c.advance(98)
    m2 = c.token_mask()
    assert m2[97] and m2[99] and not m2[98]  # another "ab", or close
    # an illegal token leaves the state UNCHANGED
    before = c.configs
    assert not c.advance(98)
    assert c.configs is before and c.n_tokens == 2
    assert c.advance(99)
    assert c.accepting()
    assert not c.token_mask().any()  # closed: zero legal continuations
    assert c.n_tokens == 3 and c.n_bytes == 3


def test_mask_matches_brute_force_oracle_along_random_paths():
    """Every step of a random legal walk, for three structurally distinct
    grammars: mask bit == clone-advance legality for EVERY token id."""
    rng = np.random.default_rng(7)
    grammars = [compile_grammar(AB_C),
                compile_grammar('start ::= [a-d]+ ("," [a-d]+)* ;'),
                json_grammar()]
    for g in grammars:
        c = GrammarConstraint(g, TABLE101)
        for _ in range(12):
            _assert_mask_matches_oracle(c)
            legal = np.flatnonzero(c.token_mask())
            if len(legal) == 0:
                break
            assert c.advance(int(rng.choice(legal)))


def test_mask_oracle_on_multibyte_token_table():
    """Trie DFS with shared prefixes: multi-byte tokens (including ids
    that are prefixes of other ids) still mask exactly per the oracle."""
    toks = [b"", b"a", b"ab", b"abc", b"b", b"c", b"ca", b"abab", b"x"]
    table = TokenTable(toks)
    c = GrammarConstraint(compile_grammar(AB_C), table)
    assert table.skipped == 1  # the empty token never enters the trie
    for _ in range(6):  # a few (ab) extensions, multi-byte tokens included
        _assert_mask_matches_oracle(c)
        legal = np.flatnonzero(c.token_mask())
        assert len(legal) > 0
        assert c.advance(int(legal[0]))
    assert c.advance(toks.index(b"ab")) and c.advance(toks.index(b"c"))
    _assert_mask_matches_oracle(c)
    assert c.accepting() and not c.token_mask().any()


def test_grammar_syntax_errors():
    for bad in ("start: 'a' ;",        # lark-style colon is not EBNF
                "start ::= 'a ;",      # unterminated string
                "start ::= [] ;",      # empty class
                "start ::= ('a' ;",    # unclosed group
                "start ::= nope ;",    # undefined nonterminal
                "start ::= [z-a] ;"):  # inverted range
        with pytest.raises(GrammarError):
            compile_grammar(bad)


def test_class_escapes_and_negation():
    g = compile_grammar(r"start ::= [^\x00-\x60] '\n' ;")
    assert _accepts(g, b"z\n")
    assert not _accepts(g, b"A\n")  # 0x41 is inside the negated range
    assert not _accepts(g, b"z")


def test_json_format_grammar():
    g = json_grammar()
    for ok in (b"null", b"true", b"-12.5e3", b'"a\\nb"', b"[1,2,[]]",
               b'{"k":{"v":[true,null]},"z":""}'):
        assert _accepts(g, ok), ok
    for bad in (b"nul", b"[1,]", b"{k:1}", b"01"):
        assert not _accepts(g, bad), bad
    # a legal PREFIX advances but does not accept
    c, ok = _walk(g, b'{"k":')
    assert ok and not c.accepting()


def test_json_schema_compilation_and_validity():
    g = compile_json_schema({
        "type": "object",
        "properties": {"ok": {"type": "boolean"},
                       "tags": {"type": "array", "items": {"type": "string"},
                                "minItems": 1, "maxItems": 2}},
        "required": ["ok", "tags"]})
    assert _accepts(g, b'{"ok":true,"tags":["a"]}')
    assert _accepts(g, b'{"ok":false,"tags":["a","b"]}')
    assert not _accepts(g, b'{"ok":1,"tags":["a"]}')       # wrong type
    assert not _accepts(g, b'{"ok":true,"tags":[]}')        # under minItems
    assert not _accepts(g, b'{"ok":true,"tags":["a","b","c"]}')  # over max
    with pytest.raises(GrammarError, match="unsupported schema key"):
        compile_json_schema({"type": "object", "additionalProperties": False})
    with pytest.raises(GrammarError, match="required"):
        compile_json_schema({"type": "object", "properties": {},
                             "required": ["ghost"]})


def test_compile_spec_strictness():
    g, kind, tool = compile_spec({"format": "json"})
    assert kind == "json" and tool is None
    g, kind, tool = compile_spec({"grammar": AB_C})
    assert kind == "grammar"
    g, kind, tool = compile_spec({"schema": {"const": 5}})
    assert kind == "schema"
    g, kind, tool = compile_spec(
        {"tool": {"name": "f", "parameters": {"const": {"q": 1}}}})
    assert kind == "tool" and tool == "f"
    for bad in ({}, {"format": "xml"}, {"grammar": AB_C, "format": "json"},
                {"mystery": 1}, {"grammar": 7}, {"schema": []},
                {"tool": {"name": "f"}}, {"tool": {"parameters": {}}},
                {"tool": {"name": "f", "parameters": {}, "x": 1}}, "nope"):
        with pytest.raises(GrammarError):
            compile_spec(bad)


def test_clone_is_independent():
    c = _con(AB_C)
    assert c.advance(97)
    d = c.clone()
    assert d.advance(98) and d.n_tokens == 2
    assert c.n_tokens == 1 and not c.token_mask()[99]
    assert (d.token_mask() != c.token_mask()).any()


def test_snapshot_restore_byte_exact():
    c = _con(AB_C)
    for t in (97, 98, 97):
        assert c.advance(t)
    snap = c.snapshot()
    json.dumps(snap)  # JSON-safe: rides session tiers cross-process
    fresh = _con(AB_C)
    fresh.restore(snap)
    assert fresh.n_tokens == 3 and fresh.n_bytes == 3
    np.testing.assert_array_equal(fresh.token_mask(), c.token_mask())
    assert fresh.accepting() == c.accepting()
    # the restored automaton continues exactly where the original would
    assert fresh.advance(98) and fresh.advance(99) and fresh.accepting()
    # CRC guards: a snapshot never silently resumes under the wrong
    # grammar or token map
    with pytest.raises(GrammarError, match="grammar crc"):
        _con(ALL_LEGAL_101).restore(snap)
    with pytest.raises(GrammarError, match="token-table crc"):
        _con(AB_C, TABLE256).restore(snap)
    with pytest.raises(GrammarError, match="version"):
        _con(AB_C).restore({"v": 2})


# ------------------------------------------------------------ the registry


def test_registry_table_cache_and_corrupt_read_recompiles(tmp_path):
    tok = ByteTokenizer()
    cache = str(tmp_path / "constrain")
    r1 = ConstrainRegistry(cache_dir=cache)
    t1 = r1.table_for(tok)
    assert r1.table_for(tok) is t1  # in-memory identity
    assert r1.stats()["table_builds"] == 1
    # a second process hits the disk artifact instead of rebuilding
    r2 = ConstrainRegistry(cache_dir=cache)
    t2 = r2.table_for(tok)
    assert r2.stats() == {**r2.stats(), "table_cache_hits": 1,
                          "table_builds": 0}
    assert t2.crc == t1.crc and t2.token_bytes == t1.token_bytes
    # chaos flips one payload byte of the cache READ: the CRC gate turns
    # it into a COUNTED re-compile, byte-identical to a cold build —
    # never an invalid token map
    chaos = ConstrainChaos(ConstrainFaultConfig(seed=3, corrupt_cache_every=1))
    r3 = ConstrainRegistry(cache_dir=cache, chaos=chaos)
    t3 = r3.table_for(tok)
    s3 = r3.stats()
    assert s3["table_cache_recompiles"] == 1 and s3["table_builds"] == 1
    assert chaos.stats()["injected_corrupt_reads"] == 1
    assert t3.crc == t1.crc and t3.token_bytes == t1.token_bytes


def test_registry_grammar_memoization_and_limits(tmp_path):
    r = ConstrainRegistry(cache_dir=str(tmp_path))
    spec = {"grammar": AB_C}
    g1 = r.grammar_for(spec)
    assert r.grammar_for(dict(spec)) is g1  # keyed by canonical JSON
    s = r.stats()
    assert s["grammar_compiles"] == 1 and s["grammar_cache_hits"] == 1
    with pytest.raises(GrammarError, match="JSON-encodable"):
        r.grammar_for({"grammar": b"bytes"})
    c = r.constraint(spec, ByteTokenizer())
    assert isinstance(c, GrammarConstraint) and c.kind == "grammar"
    assert c.table.vocab_size == 256


# ------------------------------------------------- engine: identity oracle


def _run(params, cfg, ec, prompts, make_con=None, n_tokens=10, brownout=0):
    eng = Engine(params, cfg, ec)
    eng.start()
    try:
        futs = [eng.generate_async(
            p, n_tokens, brownout=brownout,
            constrain=make_con() if make_con is not None else None)
            for p in prompts]
        out = []
        for f in futs:
            try:
                out.append(f.result(timeout=180))
            except Exception as e:  # noqa: BLE001 — chaos arms expect stalls
                out.append(e)
        return out, eng.stats
    finally:
        eng.stop()


def _text(tokens) -> str:
    return "".join(chr(t) for t in tokens)


def test_all_legal_mask_is_byte_identical_to_unconstrained(params):
    """THE byte-identity oracle: under a grammar the unconstrained output
    already complies with, the mask changes NOTHING — token-for-token
    identical across pipeline depth {0,1} x speculation {off,on}."""
    plain, _ = _run(params, CFG, EngineConfig(
        max_slots=4, num_pages=128, page_size=8, max_pages_per_slot=16,
        pipeline_depth=0), PROMPTS)
    want = [r["tokens"] for r in plain]
    for depth in (0, 1):
        for spec in (None, "prompt_lookup"):
            ec = EngineConfig(
                max_slots=4, num_pages=128, page_size=8,
                max_pages_per_slot=16, pipeline_depth=depth,
                speculative=spec, spec_ngram=1, spec_max_draft=4)
            got, stats = _run(params, CFG, ec, PROMPTS,
                              make_con=lambda: _con(ALL_LEGAL_101))
            assert [r["tokens"] for r in got] == want, (depth, spec)
            assert all(r["constrain"]["outcome"] == "valid" for r in got)
            assert stats["constrained_requests"] == len(PROMPTS)
            assert stats["constraint_stalls"] == 0
            assert (stats["free_pages"] + stats["cached_pages"]
                    == 128 - 1), stats


def test_forcing_grammar_closed_graceful_finish_without_eos(params):
    """A closed grammar on an engine with NO eos id finishes the slot
    gracefully at the exact grammar boundary — never a stall, never a
    budget-truncation."""
    ec = EngineConfig(max_slots=2, num_pages=64, page_size=8,
                      max_pages_per_slot=16)
    out, stats = _run(params, CFG, ec, [[5, 6, 7]],
                      make_con=lambda: _con('start ::= "abc" ;'),
                      n_tokens=9)
    r = out[0]
    assert r["tokens"] == [97, 98, 99]  # ord("abc")
    assert r["constrain"] == {"kind": "grammar", "outcome": "valid",
                              "n_tokens": 3, "n_bytes": 3}
    assert not r["truncated"] and stats["constraint_stalls"] == 0


def test_eos_composes_with_closed_grammar(params):
    """With stop ids configured, a closed grammar makes eos the ONLY
    legal token — the sampled eos terminates exactly like any eos."""
    ec = EngineConfig(max_slots=2, num_pages=64, page_size=8,
                      max_pages_per_slot=16, eos_ids=(100,))
    out, _ = _run(params, CFG, ec, [[5, 6, 7]],
                  make_con=lambda: _con('start ::= "abc" ;'), n_tokens=9)
    assert out[0]["tokens"] == [97, 98, 99, 100]
    assert out[0]["constrain"]["outcome"] == "valid"
    assert out[0]["constrain"]["n_tokens"] == 3  # stop ids never advance


def test_grammar_valid_always_and_truncation_reports(params):
    """The other half of the oracle: when the mask DOES bite, every
    output is a legal sentence prefix — complete iff outcome=="valid"."""
    ec = EngineConfig(max_slots=4, num_pages=128, page_size=8,
                      max_pages_per_slot=16)
    out, _ = _run(params, CFG, ec, PROMPTS, make_con=lambda: _con(AB_C),
                  n_tokens=8)
    g = compile_grammar(AB_C)
    for r in out:
        c, ok = _walk(g, _text(r["tokens"]).encode("latin-1"))
        assert ok, "constrained output is not even a legal prefix"
        assert (r["constrain"]["outcome"] == "valid") == c.accepting()
        if r["constrain"]["outcome"] == "valid":
            assert re_mod.fullmatch(r"(ab)+c", _text(r["tokens"]))


def test_spec_drafts_verified_against_automaton(params_acc):
    """Speculation composes: with REAL draft acceptance (small vocab) the
    constrained spec run is byte-identical to the unconstrained plain run
    under the all-legal grammar, and a forcing grammar still yields the
    exact forced string with drafting live."""
    prompts = [list(range(1, CFG_ACC.vocab_size)), [1, 2, 3, 4] * 4]
    plain, _ = _run(params_acc, CFG_ACC, EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16,
        pipeline_depth=0), prompts, n_tokens=24)
    spec_ec = EngineConfig(
        max_slots=2, num_pages=64, page_size=8, max_pages_per_slot=16,
        pipeline_depth=1, speculative="prompt_lookup", spec_ngram=1,
        spec_max_draft=4)
    got, stats = _run(params_acc, CFG_ACC, spec_ec, prompts,
                      make_con=lambda: _con(ALL_LEGAL_13, TABLE13),
                      n_tokens=24)
    assert [r["tokens"] for r in got] == [r["tokens"] for r in plain]
    assert stats["spec_proposed"] > 0
    forced, _ = _run(params_acc, CFG_ACC, spec_ec, [prompts[0]],
                     make_con=lambda: GrammarConstraint(
                         compile_grammar('start ::= "\\x01\\x02\\x03" ;'),
                         TABLE13), n_tokens=24)
    assert forced[0]["tokens"] == [1, 2, 3]
    assert forced[0]["constrain"]["outcome"] == "valid"


def test_automaton_snapshot_rides_preempt_resume(params):
    """A preemption storm (chaos preempt_every) swaps constrained slots
    out and back: the automaton snapshot restores byte-exact alongside
    the KV, so outputs match the storm-free constrained run."""
    base = dict(max_slots=4, num_pages=128, page_size=8,
                max_pages_per_slot=16)
    calm, _ = _run(params, CFG, EngineConfig(**base), PROMPTS,
                   make_con=lambda: _con(ALL_LEGAL_101), n_tokens=12)
    storm, stats = _run(params, CFG, EngineConfig(
        **base, chaos=FaultConfig(preempt_every=5)), PROMPTS,
        make_con=lambda: _con(ALL_LEGAL_101), n_tokens=12)
    assert stats["preemptions"] > 0
    assert [r["tokens"] for r in storm] == [r["tokens"] for r in calm]
    assert all(r["constrain"]["outcome"] == "valid" for r in storm)


def test_brownout_stage2_drops_drafts_never_the_mask(params_acc):
    """The degradation contract (overload.BROWNOUT_NEVER_DEGRADES):
    brownout stage 2 turns speculation off for the request but the
    grammar mask stays — output unchanged, zero drafts proposed."""
    ec = EngineConfig(max_slots=2, num_pages=64, page_size=8,
                      max_pages_per_slot=16, pipeline_depth=1,
                      speculative="prompt_lookup", spec_ngram=1,
                      spec_max_draft=4)
    prompts = [list(range(1, CFG_ACC.vocab_size))]
    hot, s_hot = _run(params_acc, CFG_ACC, ec, prompts,
                      make_con=lambda: _con(ALL_LEGAL_13, TABLE13),
                      n_tokens=24)
    assert s_hot["spec_proposed"] > 0
    cool, s_cool = _run(params_acc, CFG_ACC, ec, prompts,
                        make_con=lambda: _con(ALL_LEGAL_13, TABLE13),
                        n_tokens=24, brownout=2)
    assert s_cool["spec_proposed"] == 0
    assert cool[0]["tokens"] == hot[0]["tokens"]  # mask + identity intact
    assert cool[0]["constrain"]["outcome"] == "valid"


def test_brownout_never_degrades_pin():
    assert "grammar_mask" in overload.BROWNOUT_NEVER_DEGRADES


# --------------------------------------------------- engine: chaos + faults


def test_forced_stall_fails_only_victim_with_incident(params):
    """constrain chaos stall_on: the victim fails with ConstraintStall,
    the unconstrained neighbor is untouched, and the incident plane
    classifies the event as constraint_stall."""
    import time as _t
    ec = EngineConfig(max_slots=2, num_pages=64, page_size=8,
                      max_pages_per_slot=16,
                      constrain_chaos=ConstrainFaultConfig(stall_on=1),
                      incidents=True, incident_debounce_s=0.2,
                      incident_resolve_s=0.5, incident_poll_s=0.05)
    eng = Engine(params, CFG, ec)
    eng.start()
    try:
        victim = eng.generate_async([5, 6, 7], 8, constrain=_con(AB_C))
        bystander = eng.generate_async([8, 9, 10], 8)
        with pytest.raises(ConstraintStall, match="zero legal tokens"):
            victim.result(timeout=60)
        assert len(bystander.result(timeout=60)["tokens"]) == 8
        stats = eng.stats
        assert stats["constraint_stalls"] == 1
        assert stats["constrain_chaos"]["injected_stalls"] == 1
        t0 = _t.monotonic()
        while _t.monotonic() - t0 < 30:
            if any(i["cause"] == "constraint_stall"
                   for i in eng.incident_list()):
                break
            _t.sleep(0.05)
        assert any(i["cause"] == "constraint_stall"
                   for i in eng.incident_list())
    finally:
        eng.stop()


def test_seeded_stall_chaos_zero_invalid_outputs(params):
    """stall_every across a batch of constrained requests: every failure
    is a counted ConstraintStall and every SURVIVING output is fully
    grammar-valid — the chaos arm's 0-invalid-outputs gate."""
    ec = EngineConfig(max_slots=4, num_pages=128, page_size=8,
                      max_pages_per_slot=16,
                      constrain_chaos=ConstrainFaultConfig(seed=11,
                                                           stall_every=9))
    out, stats = _run(params, CFG, ec, PROMPTS + [[4, 4, 8] * 3],
                      make_con=lambda: _con(AB_C), n_tokens=8)
    failed = [r for r in out if isinstance(r, Exception)]
    lived = [r for r in out if not isinstance(r, Exception)]
    assert failed and all(isinstance(e, ConstraintStall) for e in failed)
    assert stats["constraint_stalls"] == len(failed)
    assert stats["constrain_chaos"]["injected_stalls"] >= len(failed)
    g = compile_grammar(AB_C)
    for r in lived:
        _, ok = _walk(g, _text(r["tokens"]).encode("latin-1"))
        assert ok, "chaos arm emitted a grammar-invalid token"


def test_vocab_mismatch_rejected_at_admission(params):
    eng = Engine(params, CFG, EngineConfig(max_slots=2, num_pages=64,
                                           page_size=8,
                                           max_pages_per_slot=16))
    eng.start()
    try:
        with pytest.raises(RequestError, match="vocab"):
            eng.generate_async([1, 2], 4, constrain=_con(AB_C, TABLE256))
    finally:
        eng.stop()


def test_taxonomy_rows_for_constraint_stall():
    assert EXPECTED_INCIDENT_CAUSES["constrain:stall"] == "constraint_stall"
    assert "constraint_stall" in CAUSES
    assert CAUSE_PLAYBOOK["constraint_stall"] == "observe"


def test_waterfall_carves_grammar_advance(params):
    """The latency-attribution satellite: a constrained request's
    waterfall carries a grammar_advance segment carved out of decode,
    and the partition invariant (sum == wall) holds with it present."""
    eng = Engine(params, CFG, EngineConfig(max_slots=2, num_pages=64,
                                           page_size=8,
                                           max_pages_per_slot=16))
    eng.start()
    try:
        r = eng.generate_async([5, 6, 7], 8,
                               constrain=_con(ALL_LEGAL_101)).result(
                                   timeout=120)
        wf = eng.waterfall(r["rid"])
        assert wf is not None
        assert "grammar_advance" in {s["name"] for s in wf["segments"]}
        total = sum(s["dur_s"] for s in wf["segments"])
        assert total == pytest.approx(wf["wall_s"], abs=1e-6)
    finally:
        eng.stop()


# ------------------------------------------------------- serve + ingress


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    d = tmp_path_factory.mktemp("llm")
    (d / "config.json").write_text(json.dumps(
        {"vocab_size": 256, "d_model": 32, "n_layers": 1, "n_heads": 2,
         "n_kv_heads": 1, "d_ff": 64}))
    (d / "engine.json").write_text(json.dumps(
        {"max_slots": 2, "num_pages": 64, "page_size": 8}))
    m = JetStreamModel("llm", str(d))
    m.load()
    yield m
    m.engine.stop()


def test_serve_schema_yields_structured_json_field(served):
    out = served.generate({"text_input": "q", "parameters": {
        "max_tokens": 16, "constrain": {"schema": {"const": "ok"}}}})
    assert out["text_output"] == '"ok"'
    assert out["json"] == "ok"
    rec = out["constrain"]
    assert rec["kind"] == "schema" and rec["outcome"] == "valid"


def test_serve_tool_call_field(served):
    out = served.generate({"text_input": "q", "parameters": {
        "max_tokens": 24, "constrain": {"tool": {
            "name": "lookup",
            "parameters": {"const": {"q": "hi"}}}}}})
    assert out["tool_call"] == {"name": "lookup", "arguments": {"q": "hi"}}
    assert out["constrain"]["tool"] == "lookup"
    assert out["constrain"]["outcome"] == "valid"


def test_serve_grammar_kind_has_no_parse_field(served):
    out = served.generate({"text_input": "q", "parameters": {
        "max_tokens": 8, "constrain": {"grammar": 'start ::= "abc" ;'}}})
    assert out["text_output"] == "abc"
    assert "json" not in out and "tool_call" not in out
    assert out["constrain"]["outcome"] == "valid"


def test_serve_admission_rejections(served):
    with pytest.raises(RequestError, match="exactly one of"):
        served.generate({"text_input": "q", "parameters": {
            "constrain": {"schema": {"const": 1}, "format": "json"}}})
    with pytest.raises(RequestError, match="unexpected character"):
        served.generate({"text_input": "q", "parameters": {
            "constrain": {"grammar": "start: 'a'"}}})
    with pytest.raises(RequestError, match="mutually exclusive"):
        served.generate({"text_input": "q", "parameters": {
            "constrain": {"format": "json"},
            "resume_token_ids": [1, 2, 3]}})


def test_serve_stream_emits_structured_event(served):
    pieces = list(served.generate_stream({"text_input": "q", "parameters": {
        "max_tokens": 16, "constrain": {"schema": {"const": "ok"}}}}))
    final = pieces[-1]
    assert final["constrain"]["outcome"] == "valid"
    ev = [p for p in pieces if p.get("event") == "json"]
    assert len(ev) == 1 and ev[0]["json"] == "ok"
    assert ev[0]["text_output"] == ""
    assert "".join(p.get("text_output", "") for p in pieces[:-1]) == '"ok"'


def test_serve_predict_per_instance_constraints(served):
    out = served.predict({"instances": [
        {"prompt": "q", "max_tokens": 16,
         "constrain": {"schema": {"const": "ok"}}},
        {"prompt": "r", "max_tokens": 4}]})
    assert out[0]["json"] == "ok"
    assert out[0]["constrain"]["outcome"] == "valid"
    assert "constrain" not in out[1]


def test_metrics_exposition(served):
    served.generate({"text_input": "q", "parameters": {
        "max_tokens": 16, "constrain": {"schema": {"const": "ok"}}}})
    text = served.engine.telemetry.render()
    assert 'engine_constrained_requests_total{outcome="valid"}' in text
    assert "engine_grammar_mask_seconds" in text


# ------------------------------------------------------ the OpenAI surface


def test_openai_constrain_spec_mapping():
    assert openai_constrain_spec({}) is None
    assert openai_constrain_spec(
        {"response_format": {"type": "text"}}) is None
    assert openai_constrain_spec(
        {"response_format": {"type": "json_object"}}) == {"format": "json"}
    schema = {"type": "object", "properties": {"a": {"type": "integer"}},
              "required": ["a"]}
    assert openai_constrain_spec(
        {"response_format": {"type": "json_schema",
                             "json_schema": {"schema": schema}}}
    ) == {"schema": schema}
    tools = [{"type": "function",
              "function": {"name": "f", "parameters": schema}}]
    want = {"tool": {"name": "f", "parameters": schema}}
    assert openai_constrain_spec(
        {"tools": tools, "tool_choice": "required"}) == want
    assert openai_constrain_spec(
        {"tools": tools,
         "tool_choice": {"type": "function",
                         "function": {"name": "f"}}}) == want
    assert openai_constrain_spec(
        {"tools": tools, "tool_choice": "auto"}) is None
    assert openai_constrain_spec(
        {"tools": tools, "tool_choice": "none"}) is None
    for bad in ({"response_format": {"type": "xml"}},
                {"response_format": "json"},
                {"tools": tools, "tool_choice": "maybe"},
                {"tools": tools,
                 "tool_choice": {"type": "function",
                                 "function": {"name": "ghost"}}},
                {"tools": tools + tools, "tool_choice": "required"}):
        with pytest.raises(ValueError):
            openai_constrain_spec(bad)
