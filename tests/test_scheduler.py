"""QoS scheduler tests (ISSUE 4): priority classes, EDF, fair share,
eager reaping, and preemption with KV swap/recompute — all on CPU.

The headline scenarios (ISSUE 4 acceptance):

  * a greedy request preempted mid-decode (chaos preempt storm) and resumed
    emits the IDENTICAL token sequence with 0 leaked pages — for the swap
    path, the drop-and-recompute path, and auto;
  * a flooded ``batch`` class cannot starve ``interactive`` requests;
  * priority plumbs uniformly through generate/generate_async/
    generate_stream/predict and the HTTP parsing layer, with streaming at
    parity with unary.
"""

import time

import jax
import numpy as np
import pytest

from kubeflow_tpu.serving.engine import Engine, EngineConfig
from kubeflow_tpu.serving.engine import model as M
from kubeflow_tpu.serving.engine.faults import FaultConfig
from kubeflow_tpu.serving.engine.scheduler import (
    PRIORITY_CLASSES, HostSwapStore, QosScheduler, QueueEntry,
    SchedulerConfig, normalize_priority)
from kubeflow_tpu.serving.errors import DeadlineExceeded, RequestError

pytestmark = pytest.mark.sched

CFG = M.DecoderConfig(vocab_size=101, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=128)


@pytest.fixture(scope="module")
def params():
    return M.init(jax.random.PRNGKey(0), CFG)


def _ec(**kw):
    base = dict(max_slots=4, num_pages=128, page_size=8, max_pages_per_slot=32)
    base.update(kw)
    return EngineConfig(**base)


def _wait(pred, timeout=30.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    raise TimeoutError(f"timed out waiting for {msg}")


PROMPTS = [[(i * 13 + j * 7) % (CFG.vocab_size - 1) + 1 for j in range(5 + i % 3)]
           for i in range(8)]


def _leaked(eng) -> int:
    s = eng.stats
    return (eng.ec.num_pages - 1) - s["free_pages"] - s["cached_pages"]


# ------------------------------------------------------------- pure units


def test_normalize_priority_validates():
    assert normalize_priority(None) == "interactive"
    for c in PRIORITY_CLASSES:
        assert normalize_priority(c) == c
    for bad in ("urgent", 3, "", "INTERACTIVE"):
        with pytest.raises(RequestError):
            normalize_priority(bad)


def _entry(rid, rank=0, deadline=None, aid=0, pages=1, t=0.0):
    return QueueEntry(rid=rid, rank=rank, deadline=deadline,
                      submitted_at=t, adapter_id=aid, pages=pages)


def test_scheduler_orders_by_class_then_edf():
    s = QosScheduler(SchedulerConfig())
    s.push(_entry(0, rank=1))                  # batch, no deadline
    s.push(_entry(1, rank=2))                  # best_effort
    s.push(_entry(2, rank=1, deadline=5.0))    # batch, earlier deadline
    s.push(_entry(3, rank=0))                  # interactive
    order = []
    while True:
        e = s.peek()
        if e is None:
            break
        order.append(e.rid)
        s.pop(e)
    # interactive first, then batch by EDF (deadline < none), then best_effort
    assert order == [3, 2, 0, 1]


def test_scheduler_fifo_policy_ignores_class():
    s = QosScheduler(SchedulerConfig(policy="fifo"))
    s.push(_entry(0, rank=2))
    s.push(_entry(1, rank=0))
    assert s.peek().rid == 0  # submission order, not class


def test_scheduler_fair_share_across_adapters():
    """Same class, tenant A floods before tenant B arrives: admissions must
    interleave (stride scheduling over per-adapter virtual time), not drain
    A's backlog first.  With weight 2 for A, A gets ~2 admissions per B."""
    s = QosScheduler(SchedulerConfig())
    for i in range(6):
        s.push(_entry(i, rank=1, aid=1))
    for i in range(6, 12):
        s.push(_entry(i, rank=1, aid=2))
    order = []
    while True:
        e = s.peek()
        if e is None:
            break
        order.append(e.adapter_id)
        s.pop(e)
    first6 = order[:6]
    assert first6.count(1) == 3 and first6.count(2) == 3  # interleaved

    s = QosScheduler(SchedulerConfig(), adapter_weights={1: 2.0, 2: 1.0})
    for i in range(8):
        s.push(_entry(i, rank=1, aid=1))
    for i in range(8, 12):
        s.push(_entry(i, rank=1, aid=2))
    order = []
    while True:
        e = s.peek()
        if e is None:
            break
        order.append(e.adapter_id)
        s.pop(e)
    assert order[:6].count(1) == 4  # ~2:1 service under 2:1 weights


def test_scheduler_newcomer_gets_no_free_credit():
    """An adapter joining while the incumbent's queue is momentarily empty
    (all its work decoding in slots) must start at the incumbent's virtual
    time, not zero — else it monopolizes admission for as long as the
    incumbent spent building that vtime."""
    s = QosScheduler(SchedulerConfig())
    for i in range(5):
        s.push(_entry(i, rank=1, aid=1, pages=100))
        s.pop(s.peek())  # adapter 1 banks vtime 500 and drains
    for i in range(10, 13):
        s.push(_entry(i, rank=1, aid=2, pages=100))  # B joins, queue empty
    for i in range(13, 16):
        s.push(_entry(i, rank=1, aid=1, pages=100))
    order = []
    while True:
        e = s.peek()
        if e is None:
            break
        order.append(e.adapter_id)
        s.pop(e)
    # B starts level with A (vtime 500): service interleaves [1,2,1,2,...]
    # — with vtime-0 credit B would monopolize the first 3 admissions
    assert order[:4] == [1, 2, 1, 2], order


def test_scheduler_rejects_unknown_policy():
    with pytest.raises(ValueError):
        QosScheduler(SchedulerConfig(policy="lottery"))
    with pytest.raises(ValueError):
        QosScheduler(SchedulerConfig(swap_policy="teleport"))


def test_swap_store_budget_and_accounting():
    st = HostSwapStore(max_bytes=100)
    assert st.put(1, "blob", 60)
    assert not st.put(2, "big", 60)  # over budget -> recompute fallback
    assert st.rejected == 1
    blob, n = st.pop(1)
    assert blob == "blob" and n == 60 and st.used_bytes == 0
    assert st.pop(1) is None
    assert st.put(3, "x", 100)
    st.discard(3)
    assert st.used_bytes == 0 and st.stats()["swapped_in"] == 1


# -------------------------------------------------- engine: admission order


def test_interactive_overtakes_queued_batch(params):
    """One slot held by a batch job; 2 queued batch + 1 interactive
    (submitted LAST).  The interactive request must finish first."""
    eng = Engine(params, CFG, _ec(
        max_slots=1,
        scheduler=SchedulerConfig(preemption=False)))
    eng.start()
    try:
        blocker = eng.generate_async(PROMPTS[0], 30, priority="batch")
        _wait(lambda: eng.stats["active_slots"] == 1, msg="blocker admitted")
        done = []
        futs = {}
        for name, prompt, prio in (("b1", PROMPTS[1], "batch"),
                                   ("b2", PROMPTS[2], "batch"),
                                   ("i1", PROMPTS[3], "interactive")):
            f = eng.generate_async(prompt, 4, priority=prio)
            f.add_done_callback(lambda _, n=name: done.append(n))
            futs[name] = f
        for f in futs.values():
            assert f.result(timeout=180)["num_tokens"] == 4
        blocker.result(timeout=180)
        assert done[0] == "i1", done  # class outranks submission order
    finally:
        eng.stop()


def test_edf_within_class(params):
    """Two batch requests with deadlines inverted from submission order:
    the earlier-deadline one is admitted (and finishes) first."""
    eng = Engine(params, CFG, _ec(
        max_slots=1, scheduler=SchedulerConfig(preemption=False)))
    eng.start()
    try:
        blocker = eng.generate_async(PROMPTS[0], 25, priority="batch")
        _wait(lambda: eng.stats["active_slots"] == 1, msg="blocker admitted")
        done = []
        late = eng.generate_async(PROMPTS[1], 4, priority="batch",
                                  deadline=300.0)
        soon = eng.generate_async(PROMPTS[2], 4, priority="batch",
                                  deadline=120.0)
        late.add_done_callback(lambda _: done.append("late"))
        soon.add_done_callback(lambda _: done.append("soon"))
        assert soon.result(timeout=180)["num_tokens"] == 4
        assert late.result(timeout=180)["num_tokens"] == 4
        blocker.result(timeout=180)
        assert done[0] == "soon", done
    finally:
        eng.stop()


def test_eager_queue_reaping(params):
    """Satellite: a deadline-expired queued request sheds within ticks of
    expiry — while the blocker still runs — instead of waiting to reach
    the admission head, and stops holding queue-depth budget."""
    eng = Engine(params, CFG, _ec(
        max_slots=1, max_queue_depth=2,
        scheduler=SchedulerConfig(preemption=False)))
    eng.start()
    try:
        blocker = eng.generate_async(PROMPTS[0], 200, priority="batch")
        _wait(lambda: eng.stats["active_slots"] == 1, msg="blocker admitted")
        doomed = eng.generate_async(PROMPTS[1], 4, deadline=0.05)
        t0 = time.perf_counter()
        with pytest.raises(DeadlineExceeded) as exc:
            doomed.result(timeout=60)
        assert "reaped" in str(exc.value)
        assert time.perf_counter() - t0 < 5.0
        assert not blocker.done()  # shed long before the head freed
        _wait(lambda: eng.stats["queue_depth"] == 0, msg="budget released")
        assert eng.stats["requests_shed"] == 1
        # the freed budget admits new work immediately
        follow = eng.generate_async(PROMPTS[2], 4)
        eng.cancel(blocker)
        assert follow.result(timeout=180)["num_tokens"] == 4
    finally:
        eng.stop()


# ------------------------------------------------ preemption + resume


def _run_all(eng, n_tokens=20, priority="batch"):
    futs = [eng.generate_async(p, n_tokens, priority=priority)
            for p in PROMPTS[:4]]
    return [f.result(timeout=300) for f in futs]


@pytest.mark.parametrize("mode", ["swap", "recompute", "auto"])
def test_preempt_resume_byte_identity(params, mode):
    """ISSUE 4 acceptance headline: under a chaos preemption storm, every
    preempted-then-resumed greedy request emits the identical token
    sequence, with 0 leaked pages and SERVING health after."""
    eng = Engine(params, CFG, _ec())
    eng.start()
    try:
        baseline = _run_all(eng)
    finally:
        eng.stop()

    eng = Engine(params, CFG, _ec(
        chaos=FaultConfig(preempt_every=5),
        scheduler=SchedulerConfig(swap_policy=mode, swap_min_tokens=8)))
    eng.start()
    try:
        stormed = _run_all(eng)
        for base, got in zip(baseline, stormed):
            assert got["tokens"] == base["tokens"]  # byte-identical
        s = eng.stats
        assert s["preemptions"] > 0
        assert sum(r["preemptions"] for r in stormed) == s["preemptions"]
        if mode in ("swap", "auto"):
            assert s["swapped_out"] > 0
            assert s["swapped_in"] == s["swapped_out"]
            assert s["swap_used_bytes"] == 0  # every blob restored
        else:
            assert s["swapped_out"] == 0
        assert _leaked(eng) == 0
        assert eng.health()["state"] == "SERVING"
    finally:
        eng.stop()


def test_priority_preemption_frees_slot_for_interactive(params):
    """A batch job holding the only slot is preempted for an arriving
    interactive request, then resumes and completes in full — TTFT for the
    interactive request is decoupled from the batch job's runtime."""
    eng = Engine(params, CFG, _ec(max_slots=1))
    eng.start()
    try:
        eng.generate(PROMPTS[0], 2)  # warmup compile
        t0 = time.perf_counter()
        hog = eng.generate_async(PROMPTS[0], 150, priority="batch")
        _wait(lambda: eng.stats["active_slots"] == 1, msg="hog admitted")
        inter = eng.generate_async(PROMPTS[1], 4, priority="interactive")
        ri = inter.result(timeout=120)
        t_inter = time.perf_counter() - t0
        rh = hog.result(timeout=300)
        t_hog = time.perf_counter() - t0
        assert ri["num_tokens"] == 4
        assert rh["num_tokens"] == 150  # resumed to completion
        assert rh["preemptions"] >= 1
        assert t_inter < t_hog
        assert eng.stats["preemptions"] >= 1
        assert _leaked(eng) == 0
        # the preemption left a lifecycle trace
        tr = eng.trace(rh["rid"])
        phases = [e["phase"] for e in tr["events"]]
        assert "preempted" in phases and "readmitted" in phases
    finally:
        eng.stop()


def test_flooded_batch_cannot_starve_interactive(params):
    """ISSUE 4 acceptance: a standing flood of batch-class work cannot
    starve interactive arrivals — every interactive request completes while
    most of the flood is still queued/running."""
    eng = Engine(params, CFG, _ec(
        max_slots=2, scheduler=SchedulerConfig(preemption=False)))
    eng.start()
    try:
        flood = [eng.generate_async(PROMPTS[i % 8], 40, priority="batch")
                 for i in range(10)]
        _wait(lambda: eng.stats["active_slots"] == 2, msg="flood admitted")
        inters = [eng.generate_async(PROMPTS[(i + 1) % 8], 4,
                                     priority="interactive")
                  for i in range(3)]
        for f in inters:
            assert f.result(timeout=180)["num_tokens"] == 4
        # the flood is far from drained when interactive work finished
        assert sum(f.done() for f in flood) < len(flood)
        for f in flood:
            assert f.result(timeout=600)["num_tokens"] == 40
        assert _leaked(eng) == 0
    finally:
        eng.stop()


def test_pool_pressure_watermark_evicts_without_thrash(params):
    """min_free_pages: when the pool runs below the watermark with a
    lower-priority slot decoding next to a higher-priority one, the batch
    slot is evicted for pool pressure — and the admission reserve keeps it
    QUEUED until pressure clears, instead of re-entering its own freed
    pages and swap-thrashing every tick."""
    # 31 usable pages; two requests needing up to 13 pages each leave the
    # pool under the watermark of 8 as they grow
    eng = Engine(params, CFG, _ec(
        max_slots=2, num_pages=32, max_pages_per_slot=16,
        scheduler=SchedulerConfig(min_free_pages=8, swap_policy="swap",
                                  swap_min_tokens=0)))
    eng.start()
    try:
        inter = eng.generate_async(PROMPTS[0], 90, priority="interactive")
        batch = eng.generate_async(PROMPTS[1], 90, priority="batch")
        ri = inter.result(timeout=300)
        rb = batch.result(timeout=300)
        assert ri["num_tokens"] == 90 and rb["num_tokens"] == 90
        s = eng.stats
        # pressure fired, but the reserve prevents per-tick churn: far
        # fewer evictions than the ~90 decode ticks a thrash would show
        assert 1 <= s["preemptions"] <= 10, s["preemptions"]
        assert rb["preemptions"] >= 1 and ri["preemptions"] == 0
        assert _leaked(eng) == 0
    finally:
        eng.stop()


def test_preemption_disabled_keeps_slots(params):
    """SchedulerConfig(preemption=False): a higher class reorders the
    queue but never evicts a running slot."""
    eng = Engine(params, CFG, _ec(
        max_slots=1, scheduler=SchedulerConfig(preemption=False)))
    eng.start()
    try:
        hog = eng.generate_async(PROMPTS[0], 40, priority="best_effort")
        _wait(lambda: eng.stats["active_slots"] == 1, msg="hog admitted")
        inter = eng.generate_async(PROMPTS[1], 4, priority="interactive")
        assert inter.result(timeout=300)["num_tokens"] == 4
        assert hog.result(timeout=300)["num_tokens"] == 40
        assert eng.stats["preemptions"] == 0
    finally:
        eng.stop()


def test_preemption_metrics_exposed(params):
    """stats + Prometheus surface: preemptions_total{reason,mode} and
    engine_swapped_bytes_total{direction} appear after a storm; per-class
    queue-wait histogram carries the priority label."""
    eng = Engine(params, CFG, _ec(
        chaos=FaultConfig(preempt_every=5),
        scheduler=SchedulerConfig(swap_policy="swap")))
    eng.start()
    try:
        _run_all(eng, n_tokens=15)
        s = eng.stats
        assert s["preemptions"] > 0 and s["swap_bytes_out"] > 0
        assert s["scheduler"]["policy"] == "priority"
        text = eng.telemetry.render()
        assert "engine_preemptions_total" in text
        assert 'reason="chaos"' in text and 'mode="swap"' in text
        assert "engine_swapped_bytes_total" in text
        assert 'direction="in"' in text and 'direction="out"' in text
        assert 'engine_class_queue_wait_seconds' in text
        assert 'priority="batch"' in text
    finally:
        eng.stop()


def test_cancel_of_preempted_request_resolves(params):
    """A request cancelled WHILE preempted (queued, mid-swap) resolves with
    its pre-preemption tokens and releases its swap-store bytes."""
    eng = Engine(params, CFG, _ec(
        max_slots=1, chaos=FaultConfig(preempt_every=4),
        scheduler=SchedulerConfig(swap_policy="swap")))
    eng.start()
    try:
        fut = eng.generate_async(PROMPTS[0], 150, priority="batch")
        _wait(lambda: eng.stats["preemptions"] >= 1, timeout=60,
              msg="first preemption")
        assert eng.cancel(fut)
        r = fut.result(timeout=60)
        assert r["cancelled"]
        _wait(lambda: eng.stats["swap_used_bytes"] == 0, timeout=30,
              msg="swap bytes released")
        _wait(lambda: _leaked(eng) == 0, timeout=30, msg="pages released")
    finally:
        eng.stop()


# --------------------------------------------------- HTTP plumbing parity


def test_http_priority_param_and_header(params):
    """Satellite: priority plumbs through the model layer's unary AND
    streaming paths identically, with serve.py-style validation (bad
    classes raise RequestError before any engine submission)."""
    from kubeflow_tpu.serving.engine.serve import JetStreamModel

    eng = Engine(params, CFG, _ec())
    model = JetStreamModel("m", engine=eng)
    model.load()
    try:
        payload = {"text_input": "ab",
                   "parameters": {"max_tokens": 4, "priority": "batch"}}
        out = model.generate(dict(payload))
        assert out["tokens"] == 4
        # streaming parity: same parse path, same validation
        pieces = list(model.generate_stream(dict(payload)))
        assert pieces[-1]["done"] and pieces[-1]["tokens"] == 4
        # header default applies when the param is absent
        out = model.generate({"text_input": "ab",
                              "parameters": {"max_tokens": 4}},
                             headers={"X-Priority": "best_effort"})
        assert out["tokens"] == 4
        # bad classes 400 on BOTH paths, before submission
        bad = {"text_input": "ab",
               "parameters": {"max_tokens": 4, "priority": "urgent"}}
        with pytest.raises(RequestError):
            model.generate(dict(bad))
        with pytest.raises(RequestError):
            model.generate_stream(dict(bad))  # eager parse: raises HERE
        with pytest.raises(RequestError):
            model.generate({"text_input": "ab",
                            "parameters": {"max_tokens": 4}},
                           headers={"X-Priority": "urgent"})
        # batch (predict) path: per-instance priority validated up front
        with pytest.raises(RequestError):
            model.predict({"instances": [
                {"prompt": "a", "max_tokens": 2, "priority": "nope"}]})
        out = model.predict({"instances": [
            {"prompt": "a", "max_tokens": 2, "priority": "batch"},
            {"prompt": "b", "max_tokens": 2}]},
            headers={"X-Priority": "best_effort"})
        assert [o["tokens"] for o in out] == [2, 2]
    finally:
        eng.stop()


def test_engine_rejects_bad_priority_before_submit(params):
    eng = Engine(params, CFG, _ec())
    eng.start()
    try:
        with pytest.raises(RequestError):
            eng.generate_async(PROMPTS[0], 4, priority="urgent")
        assert eng.stats["queue_depth"] == 0
    finally:
        eng.stop()
