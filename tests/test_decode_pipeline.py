"""Pipelined decode loop tests (ISSUE 5): device-resident token feedback,
async readback + commit-behind, and pipeline fences.

The contract under test: with ``pipeline_depth=1`` the engine overlaps host
orchestration with the device step, and EVERY greedy output is
byte-identical to the synchronous loop (``pipeline_depth=0``, the parity
oracle) — through admissions, EOS stops, page-boundary growth (lookahead
reservation), NaN-poisoned rows, preemption storms, pool-exhaustion
truncation, cancels, and watchdog restarts — with zero leaked KV pages.
"""

import queue
import time

import jax
import numpy as np
import pytest

from kubeflow_tpu.serving.engine import Engine, EngineConfig, SchedulerConfig
from kubeflow_tpu.serving.engine import model as M
from kubeflow_tpu.serving.engine.faults import FaultConfig
from kubeflow_tpu.serving.errors import EngineError, NonFiniteLogits, TickFailure

pytestmark = pytest.mark.pipeline

CFG = M.DecoderConfig(vocab_size=101, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=128)


@pytest.fixture(scope="module")
def params():
    return M.init(jax.random.PRNGKey(0), CFG)


def _ec(**kw):
    base = dict(max_slots=4, num_pages=128, page_size=8, max_pages_per_slot=16)
    base.update(kw)
    return EngineConfig(**base)


PROMPTS = [[(i * 13 + j * 7) % (CFG.vocab_size - 1) + 1
            for j in range(4 + i % 3)] for i in range(6)]


def _assert_no_leak(stats, num_pages=128):
    """Every usable page (page 0 is the reserved trash page) is back in the
    free list or the prefix cache."""
    assert (stats["free_pages"] + stats["cached_pages"]) == num_pages - 1, stats


def _run(params, ec, prompts=PROMPTS, n_tokens=12, stagger=0.0):
    """Submit prompts (optionally staggered to force mid-stream admits),
    collect (tokens-or-error list, stats)."""
    eng = Engine(params, CFG, ec)
    eng.start()
    try:
        futs = []
        for i, p in enumerate(prompts):
            futs.append(eng.generate_async(p, n_tokens))
            if stagger and i == len(prompts) // 2:
                time.sleep(stagger)
        out = []
        for f in futs:
            try:
                out.append(f.result(timeout=180)["tokens"])
            except EngineError as e:
                out.append(e)
        stats = eng.stats
        return out, stats, eng
    finally:
        eng.stop()


# ----------------------------------------------------------- config surface


def test_pipeline_depth_validated(params):
    with pytest.raises(ValueError, match="pipeline_depth"):
        Engine(params, CFG, _ec(pipeline_depth=2))


# ------------------------------------------------------------ greedy parity


def test_multi_slot_byte_identity_with_staggered_admits(params):
    """6 prompts over 4 slots, half submitted mid-decode: admissions and
    finishes fence the pipeline repeatedly, and every output must still be
    byte-identical to the sync loop."""
    sync, s0, _ = _run(params, _ec(pipeline_depth=0), stagger=0.2)
    pipe, s1, _ = _run(params, _ec(pipeline_depth=1), stagger=0.2)
    assert pipe == sync
    assert s0["pipeline_depth"] == 0 and s0["pipeline_fences"] == 0
    assert s1["pipeline_depth"] == 1
    _assert_no_leak(s1)


def test_single_slot_long_generation_crosses_pages(params):
    """One request generating far past its prompt's last page: the
    commit-behind lookahead must reserve each next page before the dispatch
    that writes into it (a missing page would trash-route real KV and break
    identity)."""
    prompt = PROMPTS[0]
    sync, _, _ = _run(params, _ec(pipeline_depth=0, max_slots=1),
                      prompts=[prompt], n_tokens=40)
    pipe, s1, _ = _run(params, _ec(pipeline_depth=1, max_slots=1),
                       prompts=[prompt], n_tokens=40)
    assert pipe == sync and len(pipe[0]) == 40
    _assert_no_leak(s1)


def test_eos_finish_mid_pipeline(params):
    """A row stopping on EOS finishes at the commit-behind fence while the
    next tick already ran its one extra masked step — outputs must match
    the sync loop exactly (the extra step's KV lands in reserved/trash
    pages and frees with the slot)."""
    base, _, _ = _run(params, _ec(pipeline_depth=0, max_slots=1),
                      prompts=[PROMPTS[1]], n_tokens=16)
    eos = base[0][7]  # stop on the 8th generated token
    sync, s0, _ = _run(params, _ec(pipeline_depth=0, max_slots=1, eos_ids=(eos,)),
                       prompts=[PROMPTS[1]], n_tokens=16)
    pipe, s1, _ = _run(params, _ec(pipeline_depth=1, max_slots=1, eos_ids=(eos,)),
                       prompts=[PROMPTS[1]], n_tokens=16)
    assert pipe == sync
    assert pipe[0][-1] == eos and len(pipe[0]) <= 9
    _assert_no_leak(s1)


# ------------------------------------------------------------- chaos: NaN


def test_nan_in_decode_fails_only_victim_at_fence(params):
    """A NaN aimed at one row's DECODE sample (nan_phase="decode" — it must
    survive prefill) is detected at the commit-behind fence: only the victim
    fails, every other request stays byte-identical, zero pages leak, and
    the fence is counted under reason "nan"."""
    clean, _, _ = _run(params, _ec(pipeline_depth=1))
    chaos_ec = _ec(pipeline_depth=1,
                   chaos=FaultConfig(seed=0, nan_logit_rate=1.0,
                                     target_rids=(2,), nan_phase="decode"))
    eng = Engine(params, CFG, chaos_ec)
    eng.start()
    try:
        futs = [eng.generate_async(p, 12) for p in PROMPTS]
        got = []
        for f in futs:
            try:
                got.append(f.result(timeout=180)["tokens"])
            except EngineError as e:
                got.append(e)
        for i, (want, have) in enumerate(zip(clean, got)):
            if i == 2:
                assert isinstance(have, NonFiniteLogits), have
            else:
                assert have == want, i
        stats = eng.stats
        assert stats["nan_rows"] >= 1
        assert stats["pipeline_fence_reasons"].get("nan", 0) >= 1
        _assert_no_leak(stats)
        assert eng.health()["state"] == "SERVING"
    finally:
        eng.stop()


# ------------------------------------------------------ chaos: preemption


def test_preemption_storm_mid_pipeline_byte_identical(params):
    """Forced preemptions every few ticks evict decode slots mid-pipeline:
    each eviction drains to a fence first (the swap snapshot must include
    every committed token), and all outputs stay byte-identical to an
    uncontended sync run with zero leaked pages."""
    sync, _, _ = _run(params, _ec(pipeline_depth=0, max_slots=2),
                      prompts=PROMPTS[:3], n_tokens=16)
    ec = _ec(pipeline_depth=1, max_slots=2,
             scheduler=SchedulerConfig(swap_policy="auto", swap_min_tokens=4),
             chaos=FaultConfig(seed=0, preempt_every=5))
    pipe, stats, _ = _run(params, ec, prompts=PROMPTS[:3], n_tokens=16)
    assert pipe == sync
    assert stats["preemptions"] >= 1
    assert stats["pipeline_fence_reasons"].get("preempt", 0) >= 1
    _assert_no_leak(stats)


# ------------------------------------------------- watchdog restart / drain


def test_watchdog_restart_clears_pipeline(params):
    """Loop death mid-pipeline: the supervisor discards the in-flight tick
    (never committing into reassigned slots), fails the stranded requests
    with a typed error, and the restarted loop serves new work."""
    ec = _ec(pipeline_depth=1, max_slots=2,
             watchdog_interval_s=0.05, hang_timeout_s=2.0,
             chaos=FaultConfig(seed=0, die_on_tick=8))
    eng = Engine(params, CFG, ec)
    eng.start()
    try:
        futs = [eng.generate_async(p, 64) for p in PROMPTS[:2]]
        for f in futs:
            with pytest.raises((TickFailure, EngineError)):
                f.result(timeout=60)
        t0 = time.monotonic()
        while eng.stats["restarts"] < 1 and time.monotonic() - t0 < 30:
            time.sleep(0.05)
        assert eng.stats["restarts"] == 1
        # fresh work completes on the restarted loop, still pipelined
        r = eng.generate(PROMPTS[2], 8, timeout=120)
        assert len(r["tokens"]) == 8
        assert eng.health()["state"] == "SERVING"
    finally:
        eng.stop()


# --------------------------------------------------- pool-exhaustion parity


def test_pool_exhaustion_truncates_like_sync(params):
    """When the lookahead reservation cannot cover a dispatch, the tick
    falls back to the sync path whose commit-time OOM handling truncates —
    tokens and truncated flags must match pipeline_depth=0 exactly."""
    # 2 slots x small pool: both rows grow until the pool runs dry
    kw = dict(max_slots=2, num_pages=8, page_size=8, max_pages_per_slot=8)

    def run(depth):
        eng = Engine(params, CFG, _ec(pipeline_depth=depth, **kw))
        # enqueue BEFORE start(): the loop then admits both rows in its
        # first tick (one fused prefill, fixed slot order) — submitting
        # after start() races the submitter thread against the tick loop,
        # and whichever row prefills first shifts the whole page-allocation
        # pattern, flipping WHICH row OOM-truncates between the two runs
        futs = [eng.generate_async(p, 48) for p in PROMPTS[:2]]
        eng.start()
        try:
            res = [f.result(timeout=180) for f in futs]
            stats = eng.stats
            return [(r["tokens"], r["truncated"]) for r in res], stats
        finally:
            eng.stop()

    sync, s0 = run(0)
    pipe, s1 = run(1)
    assert pipe == sync
    assert any(trunc for _, trunc in pipe)  # the scenario actually OOM'd
    _assert_no_leak(s1, num_pages=8)


# ------------------------------------------------------------------- cancel


def test_cancel_mid_decode_resolves_and_frees(params):
    eng = Engine(params, CFG, _ec(pipeline_depth=1, max_slots=1))
    eng.start()
    try:
        q: queue.Queue = queue.Queue()
        fut = eng.generate_async(PROMPTS[0], 100, stream=q)
        q.get(timeout=60)  # first token is out: the request is decoding
        assert eng.cancel(fut)
        r = fut.result(timeout=60)
        assert r["cancelled"] and r["num_tokens"] >= 1
        stats = eng.stats
        assert stats["active_slots"] == 0
        _assert_no_leak(stats)
    finally:
        eng.stop()


# -------------------------------------------------------------- observability


def test_streaming_matches_result_order(params):
    eng = Engine(params, CFG, _ec(pipeline_depth=1, max_slots=2))
    eng.start()
    try:
        stream = eng.generate_stream(PROMPTS[0], 12, timeout=120)
        items = list(stream)
        result = items[-1]
        assert items[:-1] == result["tokens"] and len(items[:-1]) == 12
    finally:
        eng.stop()


def test_fence_and_gap_metrics_exposed(params):
    """The overlap proof surfaces: engine_dispatch_gap_seconds has samples,
    engine_pipeline_fences_total renders with reason labels, and stats
    carries the fence breakdown."""
    eng = Engine(params, CFG, _ec(pipeline_depth=1))
    eng.start()
    try:
        futs = [eng.generate_async(p, 12) for p in PROMPTS]
        for f in futs:
            f.result(timeout=180)
        stats = eng.stats
        assert stats["pipeline_fences"] >= 1
        assert sum(stats["pipeline_fence_reasons"].values()) == stats["pipeline_fences"]
        assert eng.telemetry.dispatch_gap.snapshot()["count"] > 0
        text = eng.telemetry.render()
        assert "engine_dispatch_gap_seconds_bucket" in text
        assert 'engine_pipeline_fences_total{reason="' in text
    finally:
        eng.stop()
