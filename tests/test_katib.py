"""Katib tests: suggestion algorithms (unit), metrics parsing, and an e2e
LR sweep running real trial pods through the nested TPUJob stack."""

import sys

import numpy as np
import pytest

from kubeflow_tpu.core.cluster import Cluster
from kubeflow_tpu.katib import api as kapi
from kubeflow_tpu.katib.api import Parameter, experiment
from kubeflow_tpu.katib.client import KatibClient
from kubeflow_tpu.katib.controllers import install as katib_install, render_trial_spec
from kubeflow_tpu.katib.metrics import observation, parse_metrics
from kubeflow_tpu.katib.suggest import algorithm_names, get_suggester
from kubeflow_tpu.training.frameworks import install as training_install


def make_exp_obj(algorithm="random", max_trials=6, goal=None, settings=None):
    return experiment(
        "e",
        parameters=[
            Parameter("lr", "double", min=0.01, max=1.0),
            Parameter("units", "int", min=8, max=64),
            Parameter("opt", "categorical", list=["sgd", "adam"]),
        ],
        trial_spec={"apiVersion": "kubeflow.org/v1", "kind": "TPUJob", "spec": {}},
        objective_metric="accuracy",
        goal=goal,
        algorithm=algorithm,
        algorithm_settings=settings,
        max_trials=max_trials,
    )


def fake_trial(assignments, value, metric="accuracy"):
    return {
        "spec": {"parameterAssignments": [{"name": k, "value": v} for k, v in assignments.items()]},
        "status": {
            "conditions": [{"type": "Succeeded", "status": "True"}],
            "observation": {"metrics": [{"name": metric, "latest": value}]},
        },
    }


# ----------------------------------------------------------------- suggesters

def test_all_algorithms_registered():
    assert set(algorithm_names()) >= {"random", "grid", "tpe", "bayesianoptimization", "hyperband"}


@pytest.mark.parametrize("algo", ["random", "tpe", "bayesianoptimization"])
def test_suggester_respects_bounds(algo):
    exp = make_exp_obj(algo)
    trials = [fake_trial({"lr": 0.1 * i + 0.01, "units": 8 * (i + 1), "opt": "sgd"}, 0.5 + 0.01 * i)
              for i in range(8)]
    out = get_suggester(algo).suggest(exp, trials, 5)
    assert len(out) == 5
    for a in out:
        assert 0.01 <= float(a["lr"]) <= 1.0
        assert 8 <= int(a["units"]) <= 64
        assert a["opt"] in ("sgd", "adam")


def test_grid_enumerates_deterministically():
    exp = make_exp_obj("grid", settings={"default_steps": 3})
    s = get_suggester("grid")
    first = s.suggest(exp, [], 4)
    again = s.suggest(exp, [], 4)
    assert first == again
    nxt = s.suggest(exp, [{}] * 4, 4)  # 4 trials already issued
    assert nxt[0] != first[0]


def test_bayesian_concentrates_near_optimum():
    """GP-UCB should sample near the known optimum once observations exist."""
    exp = experiment(
        "e1", [Parameter("x", "double", min=0.0, max=1.0)],
        {"kind": "TPUJob", "spec": {}}, "acc", algorithm="bayesianoptimization",
        algorithm_settings={"n_initial_points": 3, "kappa": 0.5, "random_state": 1},
    )
    # objective: peak at x=0.3
    trials = [fake_trial({"x": x}, 1.0 - (x - 0.3) ** 2, "acc")
              for x in [0.0, 0.1, 0.25, 0.3, 0.35, 0.6, 0.9, 1.0]]
    out = get_suggester("bayesianoptimization").suggest(exp, trials, 8)
    xs = np.array([float(a["x"]) for a in out])
    assert (np.abs(xs - 0.3) < 0.25).mean() >= 0.5, xs


def test_hyperband_promotes_best():
    exp = experiment(
        "e2",
        [Parameter("lr", "double", min=0.1, max=1.0),
         Parameter("epochs", "double", min=1, max=9)],
        {"kind": "TPUJob", "spec": {}}, "acc", algorithm="hyperband",
        algorithm_settings={"resource_name": "epochs", "eta": 3, "min_resource": 1, "max_resource": 9},
    )
    trials = [fake_trial({"lr": lr, "epochs": 1.0}, acc, "acc")
              for lr, acc in [(0.1, 0.5), (0.4, 0.9), (0.8, 0.3)]]
    out = get_suggester("hyperband").suggest(exp, trials, 1)
    # best lr=0.4 promoted to epochs=3
    assert float(out[0]["lr"]) == 0.4
    assert float(out[0]["epochs"]) == 3.0


def running_trial(assignments):
    return {
        "spec": {"parameterAssignments": [{"name": k, "value": v} for k, v in assignments.items()]},
        "status": {"conditions": [{"type": "Running", "status": "True"}]},
    }


def test_hyperband_no_duplicate_promotion_while_running():
    """A promotion issued last round but still running must not be re-issued."""
    exp = experiment(
        "e3",
        [Parameter("lr", "double", min=0.1, max=1.0),
         Parameter("epochs", "double", min=1, max=9)],
        {"kind": "TPUJob", "spec": {}}, "acc", algorithm="hyperband",
        algorithm_settings={"resource_name": "epochs", "eta": 3, "min_resource": 1, "max_resource": 9},
    )
    trials = [fake_trial({"lr": lr, "epochs": 1.0}, acc, "acc")
              for lr, acc in [(0.1, 0.5), (0.4, 0.9), (0.8, 0.3)]]
    trials.append(running_trial({"lr": 0.4, "epochs": 3.0}))  # the earlier promotion
    out = get_suggester("hyperband").suggest(exp, trials, 2)
    for a in out:
        assert not (float(a["lr"]) == 0.4 and float(a["epochs"]) == 3.0), out
        # unevaluated rung-3 placeholder must not cascade to rung 9 either
        assert float(a["epochs"]) == 1.0, out


def test_random_state_zero_is_deterministic():
    exp = make_exp_obj("random", settings={"random_state": 0})
    a = get_suggester("random").suggest(exp, [], 4)
    b = get_suggester("random").suggest(exp, [], 4)
    assert a == b


# ------------------------------------------------------------------- metrics

def test_parse_metrics_formats():
    log = """
epoch 1: accuracy=0.81 loss=0.9
epoch 2: accuracy=0.92 loss=0.4
{"accuracy": 0.95, "loss": 0.2}
noise accuracy-ish=7 other=3
final accuracy=0.93
"""
    out = parse_metrics(log, ["accuracy", "loss"])
    assert out["accuracy"] == [0.81, 0.92, 0.95, 0.93]
    assert out["loss"] == [0.9, 0.4, 0.2]
    obs = observation(log, ["accuracy"])
    m = obs["metrics"][0]
    assert m["latest"] == 0.93 and m["max"] == 0.95 and m["min"] == 0.81


def test_render_trial_spec_substitution():
    template = {
        "trialParameters": [{"name": "learningRate", "reference": "lr"}],
        "trialSpec": {
            "kind": "TPUJob",
            "spec": {"env": [{"name": "LR", "value": "${trialParameters.learningRate}"}],
                     "cmd": ["--lr=${trialParameters.learningRate}"]},
        },
    }
    out = render_trial_spec(template, {"lr": 0.25})
    assert out["spec"]["env"][0]["value"] == "0.25"
    assert out["spec"]["cmd"][0] == "--lr=0.25"
    with pytest.raises(KeyError):
        render_trial_spec(template, {"other": 1})


# ----------------------------------------------------------------------- e2e

TRIAL_CODE = (
    "import os, math\n"
    "lr = float(os.environ['LR'])\n"
    "acc = 1.0 - (lr - 0.1) ** 2\n"
    "print(f'accuracy={acc:.6f}')\n"
)


def _sweep_spec(name, algorithm, max_trials, goal=None):
    trial_spec = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "TPUJob",
        "spec": {
            "replicaSpecs": {
                "Worker": {
                    "replicas": 1,
                    "restartPolicy": "Never",
                    "template": {"spec": {"containers": [{
                        "name": "main",
                        "command": [sys.executable, "-u", "-c", TRIAL_CODE],
                        "env": [{"name": "LR", "value": "${trialParameters.lr}"}],
                    }]}},
                }
            },
            "runPolicy": {"cleanPodPolicy": "None"},
        },
    }
    return experiment(
        name,
        parameters=[Parameter("lr", "double", min=0.01, max=1.0)],
        trial_spec=trial_spec,
        objective_metric="accuracy",
        objective_type="maximize",
        goal=goal,
        algorithm=algorithm,
        max_trials=max_trials,
        parallel_trials=3,
    )


@pytest.fixture()
def kcluster():
    c = Cluster(cpu_nodes=1)
    training_install(c.api, c.manager)
    katib_install(c.api, c.manager, c.logs)
    yield c
    c.shutdown()


def test_experiment_random_lr_sweep_e2e(kcluster):
    client = KatibClient(kcluster)
    client.create_experiment(_sweep_spec("sweep", "random", max_trials=5))
    assert client.wait_for_experiment("sweep", timeout=300) == kapi.SUCCEEDED

    exp = client.get_experiment("sweep")
    assert exp["status"]["trialsSucceeded"] == 5
    optimal = client.get_optimal_trial("sweep")
    assert optimal is not None
    # optimal is the max over observed trials
    best_seen = max(
        m["latest"]
        for t in client.list_trials("sweep")
        for m in t.get("status", {}).get("observation", {}).get("metrics", [])
        if m["name"] == "accuracy"
    )
    got = [m for m in optimal["observation"]["metrics"] if m["name"] == "accuracy"][0]["latest"]
    assert got == best_seen


def test_experiment_goal_early_stop(kcluster):
    client = KatibClient(kcluster)
    # accuracy at lr in [0.01,1.0] is >= 1-(0.9)^2 = 0.19; goal 0.0 met by any trial
    client.create_experiment(_sweep_spec("goal", "random", max_trials=50, goal=0.05))
    assert client.wait_for_experiment("goal", timeout=300) == kapi.SUCCEEDED
    exp = client.get_experiment("goal")
    # stopped well before maxTrials
    assert exp["status"]["trialsSucceeded"] < 50
    reason = [c for c in exp["status"]["conditions"] if c["type"] == kapi.SUCCEEDED][0]["reason"]
    assert reason == "GoalReached"


def test_grid_exhaustion_ends_experiment(kcluster):
    """A grid smaller than maxTrialCount must end with SuggestionEndReached,
    not hang (the experiment used to stay Running forever)."""
    client = KatibClient(kcluster)
    spec = _sweep_spec("smallgrid", "grid", max_trials=10)
    spec["spec"]["algorithm"]["algorithmSettings"] = [{"name": "default_steps", "value": "3"}]
    client.create_experiment(spec)
    assert client.wait_for_experiment("smallgrid", timeout=300) == kapi.SUCCEEDED
    exp = client.get_experiment("smallgrid")
    assert exp["status"]["trialsSucceeded"] == 3  # the full 3-point grid
    reason = [c for c in exp["status"]["conditions"] if c["type"] == kapi.SUCCEEDED][0]["reason"]
    assert reason == "SuggestionEndReached"


def test_trial_metrics_unavailable_fails(kcluster):
    client = KatibClient(kcluster)
    spec = _sweep_spec("nometrics", "random", max_trials=2)
    # trial prints nothing
    spec["spec"]["trialTemplate"]["trialSpec"]["spec"]["replicaSpecs"]["Worker"]["template"][
        "spec"]["containers"][0]["command"] = [sys.executable, "-c", "print('no metrics here')"]
    spec["spec"]["maxFailedTrialCount"] = 1
    client.create_experiment(spec)
    assert client.wait_for_experiment("nometrics", timeout=300) == kapi.FAILED
