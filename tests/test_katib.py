"""Katib tests: suggestion algorithms (unit), metrics parsing, and an e2e
LR sweep running real trial pods through the nested TPUJob stack."""

import sys

import numpy as np
import pytest

from kubeflow_tpu.core.cluster import Cluster
from kubeflow_tpu.core.conditions import has_condition
from kubeflow_tpu.katib import api as kapi
from kubeflow_tpu.katib.api import Parameter, experiment
from kubeflow_tpu.katib.client import KatibClient
from kubeflow_tpu.katib.controllers import install as katib_install, render_trial_spec
from kubeflow_tpu.katib.metrics import (TFEventWriter, observation, parse_metrics,
                                        parse_tfevent_dir)
from kubeflow_tpu.katib.obslog import ObservationStore
from kubeflow_tpu.katib.service import KatibService
from kubeflow_tpu.katib.suggest import algorithm_names, get_suggester
from kubeflow_tpu.training import api as tapi
from kubeflow_tpu.training.frameworks import install as training_install


def make_exp_obj(algorithm="random", max_trials=6, goal=None, settings=None):
    return experiment(
        "e",
        parameters=[
            Parameter("lr", "double", min=0.01, max=1.0),
            Parameter("units", "int", min=8, max=64),
            Parameter("opt", "categorical", list=["sgd", "adam"]),
        ],
        trial_spec={"apiVersion": "kubeflow.org/v1", "kind": "TPUJob", "spec": {}},
        objective_metric="accuracy",
        goal=goal,
        algorithm=algorithm,
        algorithm_settings=settings,
        max_trials=max_trials,
    )


def fake_trial(assignments, value, metric="accuracy"):
    return {
        "spec": {"parameterAssignments": [{"name": k, "value": v} for k, v in assignments.items()]},
        "status": {
            "conditions": [{"type": "Succeeded", "status": "True"}],
            "observation": {"metrics": [{"name": metric, "latest": value}]},
        },
    }


# ----------------------------------------------------------------- suggesters

def test_all_algorithms_registered():
    assert set(algorithm_names()) >= {"random", "grid", "tpe", "bayesianoptimization", "hyperband"}


@pytest.mark.parametrize("algo", ["random", "tpe", "bayesianoptimization"])
def test_suggester_respects_bounds(algo):
    exp = make_exp_obj(algo)
    trials = [fake_trial({"lr": 0.1 * i + 0.01, "units": 8 * (i + 1), "opt": "sgd"}, 0.5 + 0.01 * i)
              for i in range(8)]
    out = get_suggester(algo).suggest(exp, trials, 5)
    assert len(out) == 5
    for a in out:
        assert 0.01 <= float(a["lr"]) <= 1.0
        assert 8 <= int(a["units"]) <= 64
        assert a["opt"] in ("sgd", "adam")


def test_grid_enumerates_deterministically():
    exp = make_exp_obj("grid", settings={"default_steps": 3})
    s = get_suggester("grid")
    first = s.suggest(exp, [], 4)
    again = s.suggest(exp, [], 4)
    assert first == again
    nxt = s.suggest(exp, [{}] * 4, 4)  # 4 trials already issued
    assert nxt[0] != first[0]


def test_bayesian_concentrates_near_optimum():
    """GP-UCB should sample near the known optimum once observations exist."""
    exp = experiment(
        "e1", [Parameter("x", "double", min=0.0, max=1.0)],
        {"kind": "TPUJob", "spec": {}}, "acc", algorithm="bayesianoptimization",
        algorithm_settings={"n_initial_points": 3, "kappa": 0.5, "random_state": 1},
    )
    # objective: peak at x=0.3
    trials = [fake_trial({"x": x}, 1.0 - (x - 0.3) ** 2, "acc")
              for x in [0.0, 0.1, 0.25, 0.3, 0.35, 0.6, 0.9, 1.0]]
    out = get_suggester("bayesianoptimization").suggest(exp, trials, 8)
    xs = np.array([float(a["x"]) for a in out])
    assert (np.abs(xs - 0.3) < 0.25).mean() >= 0.5, xs


def test_hyperband_promotes_best():
    exp = experiment(
        "e2",
        [Parameter("lr", "double", min=0.1, max=1.0),
         Parameter("epochs", "double", min=1, max=9)],
        {"kind": "TPUJob", "spec": {}}, "acc", algorithm="hyperband",
        algorithm_settings={"resource_name": "epochs", "eta": 3, "min_resource": 1, "max_resource": 9},
    )
    trials = [fake_trial({"lr": lr, "epochs": 1.0}, acc, "acc")
              for lr, acc in [(0.1, 0.5), (0.4, 0.9), (0.8, 0.3)]]
    out = get_suggester("hyperband").suggest(exp, trials, 1)
    # best lr=0.4 promoted to epochs=3
    assert float(out[0]["lr"]) == 0.4
    assert float(out[0]["epochs"]) == 3.0


def running_trial(assignments):
    return {
        "spec": {"parameterAssignments": [{"name": k, "value": v} for k, v in assignments.items()]},
        "status": {"conditions": [{"type": "Running", "status": "True"}]},
    }


def test_hyperband_no_duplicate_promotion_while_running():
    """A promotion issued last round but still running must not be re-issued."""
    exp = experiment(
        "e3",
        [Parameter("lr", "double", min=0.1, max=1.0),
         Parameter("epochs", "double", min=1, max=9)],
        {"kind": "TPUJob", "spec": {}}, "acc", algorithm="hyperband",
        algorithm_settings={"resource_name": "epochs", "eta": 3, "min_resource": 1, "max_resource": 9},
    )
    trials = [fake_trial({"lr": lr, "epochs": 1.0}, acc, "acc")
              for lr, acc in [(0.1, 0.5), (0.4, 0.9), (0.8, 0.3)]]
    trials.append(running_trial({"lr": 0.4, "epochs": 3.0}))  # the earlier promotion
    out = get_suggester("hyperband").suggest(exp, trials, 2)
    for a in out:
        assert not (float(a["lr"]) == 0.4 and float(a["epochs"]) == 3.0), out
        # unevaluated rung-3 placeholder must not cascade to rung 9 either
        assert float(a["epochs"]) == 1.0, out


def test_random_state_zero_is_deterministic():
    exp = make_exp_obj("random", settings={"random_state": 0})
    a = get_suggester("random").suggest(exp, [], 4)
    b = get_suggester("random").suggest(exp, [], 4)
    assert a == b


# ------------------------------------------------------------------- metrics

def test_parse_metrics_formats():
    log = """
epoch 1: accuracy=0.81 loss=0.9
epoch 2: accuracy=0.92 loss=0.4
{"accuracy": 0.95, "loss": 0.2}
noise accuracy-ish=7 other=3
final accuracy=0.93
"""
    out = parse_metrics(log, ["accuracy", "loss"])
    assert out["accuracy"] == [0.81, 0.92, 0.95, 0.93]
    assert out["loss"] == [0.9, 0.4, 0.2]
    obs = observation(log, ["accuracy"])
    m = obs["metrics"][0]
    assert m["latest"] == 0.93 and m["max"] == 0.95 and m["min"] == 0.81


def test_render_trial_spec_substitution():
    template = {
        "trialParameters": [{"name": "learningRate", "reference": "lr"}],
        "trialSpec": {
            "kind": "TPUJob",
            "spec": {"env": [{"name": "LR", "value": "${trialParameters.learningRate}"}],
                     "cmd": ["--lr=${trialParameters.learningRate}"]},
        },
    }
    out = render_trial_spec(template, {"lr": 0.25})
    assert out["spec"]["env"][0]["value"] == "0.25"
    assert out["spec"]["cmd"][0] == "--lr=0.25"
    with pytest.raises(KeyError):
        render_trial_spec(template, {"other": 1})


# ----------------------------------------------------------------------- e2e

TRIAL_CODE = (
    "import os, math\n"
    "lr = float(os.environ['LR'])\n"
    "acc = 1.0 - (lr - 0.1) ** 2\n"
    "print(f'accuracy={acc:.6f}')\n"
)


def _sweep_spec(name, algorithm, max_trials, goal=None):
    trial_spec = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "TPUJob",
        "spec": {
            "replicaSpecs": {
                "Worker": {
                    "replicas": 1,
                    "restartPolicy": "Never",
                    "template": {"spec": {"containers": [{
                        "name": "main",
                        "command": [sys.executable, "-u", "-c", TRIAL_CODE],
                        "env": [{"name": "LR", "value": "${trialParameters.lr}"}],
                    }]}},
                }
            },
            "runPolicy": {"cleanPodPolicy": "None"},
        },
    }
    return experiment(
        name,
        parameters=[Parameter("lr", "double", min=0.01, max=1.0)],
        trial_spec=trial_spec,
        objective_metric="accuracy",
        objective_type="maximize",
        goal=goal,
        algorithm=algorithm,
        max_trials=max_trials,
        parallel_trials=3,
    )


@pytest.fixture()
def kcluster():
    c = Cluster(cpu_nodes=1)
    training_install(c.api, c.manager)
    c.katib = katib_install(c.api, c.manager, c.logs)  # (exp, sug, trial) ctrls
    yield c
    c.shutdown()


@pytest.mark.slow
def test_experiment_random_lr_sweep_e2e(kcluster):
    client = KatibClient(kcluster)
    client.create_experiment(_sweep_spec("sweep", "random", max_trials=5))
    assert client.wait_for_experiment("sweep", timeout=300) == kapi.SUCCEEDED

    exp = client.get_experiment("sweep")
    assert exp["status"]["trialsSucceeded"] == 5
    optimal = client.get_optimal_trial("sweep")
    assert optimal is not None
    # optimal is the max over observed trials
    best_seen = max(
        m["latest"]
        for t in client.list_trials("sweep")
        for m in t.get("status", {}).get("observation", {}).get("metrics", [])
        if m["name"] == "accuracy"
    )
    got = [m for m in optimal["observation"]["metrics"] if m["name"] == "accuracy"][0]["latest"]
    assert got == best_seen


@pytest.mark.slow
def test_experiment_goal_early_stop(kcluster):
    client = KatibClient(kcluster)
    # accuracy at lr in [0.01,1.0] is >= 1-(0.9)^2 = 0.19; goal 0.0 met by any trial
    client.create_experiment(_sweep_spec("goal", "random", max_trials=50, goal=0.05))
    assert client.wait_for_experiment("goal", timeout=300) == kapi.SUCCEEDED
    exp = client.get_experiment("goal")
    # stopped well before maxTrials
    assert exp["status"]["trialsSucceeded"] < 50
    reason = [c for c in exp["status"]["conditions"] if c["type"] == kapi.SUCCEEDED][0]["reason"]
    assert reason == "GoalReached"


@pytest.mark.slow  # fast lane must stay under its 5-min budget (r1 #10)
def test_grid_exhaustion_ends_experiment(kcluster):
    """A grid smaller than maxTrialCount must end with SuggestionEndReached,
    not hang (the experiment used to stay Running forever)."""
    client = KatibClient(kcluster)
    spec = _sweep_spec("smallgrid", "grid", max_trials=10)
    spec["spec"]["algorithm"]["algorithmSettings"] = [{"name": "default_steps", "value": "3"}]
    client.create_experiment(spec)
    assert client.wait_for_experiment("smallgrid", timeout=300) == kapi.SUCCEEDED
    exp = client.get_experiment("smallgrid")
    assert exp["status"]["trialsSucceeded"] == 3  # the full 3-point grid
    reason = [c for c in exp["status"]["conditions"] if c["type"] == kapi.SUCCEEDED][0]["reason"]
    assert reason == "SuggestionEndReached"


# -------------------------------------------------- observation-log store

@pytest.mark.slow
def test_push_collector_sidecar_e2e(kcluster):
    """Upstream sidecar architecture (VERDICT r2 #8): collector.kind 'Push'
    → the pod webhook injects collector_main.py as a sidecar container; it
    tails the main log and pushes to the db-manager HTTP service; the trial
    controller never pulls.  The experiment must succeed with observations
    that can only have come through the push path."""
    client = KatibClient(kcluster)
    spec = _sweep_spec("pushsweep", "random", max_trials=3)
    spec["spec"]["metricsCollectorSpec"] = {"collector": {"kind": "Push"}}
    client.create_experiment(spec)
    assert client.wait_for_experiment("pushsweep", timeout=300) == kapi.SUCCEEDED
    exp = client.get_experiment("pushsweep")
    assert exp["status"]["trialsSucceeded"] == 3
    # the store got its series via HTTP report (pull is disabled for Push)
    trial_ctrl = kcluster.katib[2]
    trials = client.list_trials("pushsweep")
    for t in trials:
        name = t["metadata"]["name"]
        assert trial_ctrl.store.count(name, "accuracy") > 0, name
        # and the trial observation was built from it
        obs = t["status"]["observation"]["metrics"]
        assert any(m["name"] == "accuracy" for m in obs)
        # the sidecar container was actually injected into the pod spec
    pods = kcluster.api.list("Pod")
    trial_pods = [p for p in pods
                  if p["metadata"].get("labels", {}).get(tapi.LABEL_JOB_NAME, "").startswith("pushsweep")]
    assert trial_pods, "trial pods were cleaned before inspection"
    for p in trial_pods:
        names = [c.get("name") for c in p["spec"]["containers"]]
        assert "metrics-collector" in names, names


def test_observation_store_roundtrip_and_wal(tmp_path):
    path = str(tmp_path / "obs.wal")
    st = ObservationStore(path)
    for i, v in enumerate([0.5, 0.7, 0.9]):
        st.report("t1", "accuracy", v, step=i)
    st.report("t1", "loss", 0.3)
    st.report("t2", "accuracy", 0.4)
    assert st.count("t1", "accuracy") == 3
    assert st.get_log("t1", "accuracy") == [(0, 0.5), (1, 0.7), (2, 0.9)]
    assert st.get_log("t1", "accuracy", start=2) == [(2, 0.9)]
    assert st.latest("t1", "accuracy") == 0.9
    assert st.latest("t1", "nope") is None
    assert st.trials() == ["t1", "t2"]
    assert st.metrics("t1") == ["accuracy", "loss"]
    obs = st.observation("t1", ["accuracy"])
    assert obs["metrics"][0] == {"name": "accuracy", "latest": 0.9, "min": 0.5, "max": 0.9}
    st.close()

    # durability: reopen replays the WAL
    st2 = ObservationStore(path)
    assert st2.get_log("t1", "accuracy") == [(0, 0.5), (1, 0.7), (2, 0.9)]
    assert st2.trials() == ["t1", "t2"]
    st2.close()

    # crash-truncated tail is dropped, prefix survives
    with open(path, "r+b") as f:
        f.truncate(max(0, tmp_path.joinpath("obs.wal").stat().st_size - 7))
    st3 = ObservationStore(path)
    assert st3.count("t1", "accuracy") >= 2
    st3.close()


def test_tfevent_writer_parser_roundtrip(tmp_path):
    w = TFEventWriter(str(tmp_path))
    for step, (acc, loss) in enumerate([(0.6, 0.9), (0.8, 0.5), (0.9, 0.2)]):
        w.scalar("accuracy", acc, step)
        w.scalar("loss", loss, step)
    w.close()
    out = parse_tfevent_dir(str(tmp_path), ["accuracy", "loss"])
    assert [s for s, _ in out["accuracy"]] == [0, 1, 2]
    assert [round(v, 4) for _, v in out["accuracy"]] == [0.6, 0.8, 0.9]
    assert [round(v, 4) for _, v in out["loss"]] == [0.9, 0.5, 0.2]
    assert parse_tfevent_dir(str(tmp_path / "missing"), ["accuracy"]) == {"accuracy": []}


SERIES_TRIAL_CODE = (
    "import os\n"
    "lr = float(os.environ['LR'])\n"
    "for i, a in enumerate([0.5, 0.7, 0.9]):\n"
    "    print(f'accuracy={a}', flush=True)\n"
)


def test_observation_series_survive_pod_gc_and_service(kcluster):
    """Intermediate series land in the store (db-manager parity), survive pod
    deletion, and the UI data endpoints serve them."""
    client = KatibClient(kcluster)
    spec = _sweep_spec("series", "random", max_trials=2)
    spec["spec"]["trialTemplate"]["trialSpec"]["spec"]["replicaSpecs"]["Worker"]["template"][
        "spec"]["containers"][0]["command"] = [sys.executable, "-u", "-c", SERIES_TRIAL_CODE]
    client.create_experiment(spec)
    assert client.wait_for_experiment("series", timeout=300) == kapi.SUCCEEDED

    store = kcluster.katib[2].store
    trials = client.list_trials("series")
    assert len(trials) == 2
    tname = trials[0]["metadata"]["name"]
    series = store.get_log(tname, "accuracy")
    assert [v for _, v in series] == [0.5, 0.7, 0.9]

    # pod GC: delete every trial pod — the series must outlive them
    for pod in kcluster.api.list("Pod"):
        kcluster.api.try_delete("Pod", pod["metadata"]["name"], pod["metadata"].get("namespace", "default"))
    kcluster.settle()
    assert store.get_log(tname, "accuracy") == series

    svc = KatibService(kcluster.api, store)
    exps = svc.list_experiments()
    assert [e["name"] for e in exps] == ["series"]
    assert exps[0]["status"] == "Succeeded" and exps[0]["trialsSucceeded"] == 2
    detail = svc.get_experiment("series")
    assert detail["currentOptimalTrial"] is not None
    assert len(detail["trials"]) == 2
    tdetail = svc.get_trial(tname)
    assert tdetail["status"] == "Succeeded"
    assert tdetail["observationLog"]["accuracy"] == [
        {"step": s, "value": v} for s, v in series]
    assert svc.get_trial("missing") is None


def test_tfevent_collector_trial_e2e(kcluster, tmp_path):
    """A trial whose metrics come from TFEvent files, not stdout (SURVEY.md
    §2a metrics-collectors row: tfevent-metricscollector)."""
    logdir = str(tmp_path / "tb")
    code = (
        "import os, sys\n"
        "sys.path.insert(0, os.environ['KFT_ROOT'])\n"
        "from kubeflow_tpu.katib.metrics import TFEventWriter\n"
        "w = TFEventWriter(os.environ['LOGDIR'])\n"
        "for i, a in enumerate([0.55, 0.75]):\n"
        "    w.scalar('accuracy', a, i)\n"
        "w.close()\n"
    )
    client = KatibClient(kcluster)
    spec = _sweep_spec("tfev", "random", max_trials=1)
    container = spec["spec"]["trialTemplate"]["trialSpec"]["spec"]["replicaSpecs"]["Worker"][
        "template"]["spec"]["containers"][0]
    container["command"] = [sys.executable, "-u", "-c", code]
    container["env"] += [{"name": "LOGDIR", "value": logdir},
                        {"name": "KFT_ROOT", "value": str(__import__("pathlib").Path(__file__).parent.parent)}]
    spec["spec"]["metricsCollectorSpec"] = {
        "collector": {"kind": "TFEvent"},
        "source": {"fileSystemPath": {"path": logdir}},
    }
    client.create_experiment(spec)
    assert client.wait_for_experiment("tfev", timeout=300) == kapi.SUCCEEDED
    store = kcluster.katib[2].store
    tname = client.list_trials("tfev")[0]["metadata"]["name"]
    assert [round(v, 4) for _, v in store.get_log(tname, "accuracy")] == [0.55, 0.75]


def test_trial_metrics_unavailable_fails(kcluster):
    client = KatibClient(kcluster)
    spec = _sweep_spec("nometrics", "random", max_trials=2)
    # trial prints nothing
    spec["spec"]["trialTemplate"]["trialSpec"]["spec"]["replicaSpecs"]["Worker"]["template"][
        "spec"]["containers"][0]["command"] = [sys.executable, "-c", "print('no metrics here')"]
    spec["spec"]["maxFailedTrialCount"] = 1
    client.create_experiment(spec)
    assert client.wait_for_experiment("nometrics", timeout=300) == kapi.FAILED


# ------------------------------------------------------------------- NAS

def test_enas_converges_to_good_ops():
    """ENAS REINFORCE controller: reward = fraction of edges set to 'conv3';
    after a few rounds the policy must clearly beat uniform-random (0.25)."""
    exp = experiment(
        "nas",
        [Parameter(f"layer_{i}_op", "categorical", list=["conv3", "conv5", "skip", "pool"])
         for i in range(4)],
        {"kind": "TPUJob", "spec": {}}, "acc", algorithm="enas",
        algorithm_settings={"random_state": 0},
    )
    trials = []
    for _ in range(12):
        for arch in get_suggester("enas").suggest(exp, trials, 3):
            acc = sum(v == "conv3" for v in arch.values()) / 4
            trials.append(fake_trial(arch, acc, "acc"))
    final = get_suggester("enas").suggest(exp, trials, 10)
    frac = np.mean([sum(v == "conv3" for v in a.values()) / 4 for a in final])
    assert frac >= 0.6, f"policy fraction {frac} (random would be 0.25)"
    # determinism: same history → same proposals
    assert final == get_suggester("enas").suggest(exp, trials, 10)


def test_nas_config_expands_to_parameters():
    """Upstream-style spec.nasConfig expands into categorical edge params."""
    from kubeflow_tpu.core.api import APIServer

    api = APIServer()
    kapi.register(api)
    obj = {
        "apiVersion": kapi.API_VERSION,
        "kind": "Experiment",
        "metadata": {"name": "nascfg"},
        "spec": {
            "objective": {"type": "maximize", "objectiveMetricName": "acc"},
            "algorithm": {"algorithmName": "enas"},
            "nasConfig": {
                "graphConfig": {"numLayers": 3},
                "operations": [{"operationType": "conv3"}, {"operationType": "skip"}],
            },
            "trialTemplate": {"trialSpec": {"kind": "TPUJob", "spec": {}}},
        },
    }
    created = api.create(obj)
    params = created["spec"]["parameters"]
    assert [p["name"] for p in params] == ["layer_0_op", "layer_1_op", "layer_2_op"]
    assert params[0]["feasibleSpace"]["list"] == ["conv3", "skip"]


@pytest.mark.slow
def test_obslog_sanitizer_builds():
    """SURVEY.md §5: the C++ observation-log core builds under ASAN/TSAN."""
    import os
    import subprocess

    d = os.path.join(os.path.dirname(__file__), "..", "kubeflow_tpu", "katib")
    try:
        for target in ("asan", "tsan"):
            subprocess.run(["make", target], cwd=d, check=True, capture_output=True)
    finally:
        subprocess.run(["make", "clean"], cwd=d, capture_output=True)


def test_darts_suggester_emits_search_settings():
    """DARTS (upstream shape): the service emits one suggestion carrying the
    search settings; the differentiable search runs inside the trial."""
    exp = experiment(
        "nasd", [Parameter("seed", "int", min=0, max=9999)],
        {"kind": "TPUJob", "spec": {}}, "val_acc", algorithm="darts",
        algorithm_settings={"num_layers": 4, "search_steps": 250, "random_state": 7},
    )
    out = get_suggester("darts").suggest(exp, [], 2)
    assert len(out) == 2
    assert out[0]["num_layers"] == "4" and out[0]["search_steps"] == "250"
    assert out[0]["seed"] != out[1]["seed"]
    assert out == get_suggester("darts").suggest(exp, [], 2)  # deterministic


@pytest.mark.slow
def test_darts_trial_e2e_recovers_genotype(kcluster):
    """Full DARTS path: experiment → trial pod running the differentiable
    search → objective from the discretized architecture; the synthetic
    task's genotype (all relu_linear) must be recovered."""
    trial_spec = {
        "apiVersion": "kubeflow.org/v1", "kind": "TPUJob",
        "spec": {"replicaSpecs": {"Worker": {
            "replicas": 1, "restartPolicy": "Never",
            "template": {"spec": {"containers": [{
                "name": "main",
                "command": [sys.executable, "-u", "-m", "kubeflow_tpu.examples.darts_worker"],
                "env": [
                    {"name": "JAX_PLATFORMS", "value": "cpu"},
                    {"name": "PYTHONPATH", "value": "/root/repo"},
                    {"name": "NUM_LAYERS", "value": "${trialParameters.numLayers}"},
                    {"name": "SEARCH_STEPS", "value": "${trialParameters.searchSteps}"},
                    {"name": "SEED", "value": "${trialParameters.seed}"},
                ],
            }]}},
        }}},
    }
    spec = experiment(
        "dartse", [Parameter("seed", "int", min=0, max=9999)], trial_spec,
        "val_acc", algorithm="darts", max_trials=1,
        algorithm_settings={"num_layers": "4", "search_steps": "300"},
        trial_parameters=[
            {"name": "numLayers", "reference": "num_layers"},
            {"name": "searchSteps", "reference": "search_steps"},
            {"name": "seed", "reference": "seed"},
        ],
    )
    client = KatibClient(kcluster)
    client.create_experiment(spec)
    assert client.wait_for_experiment("dartse", timeout=600) == kapi.SUCCEEDED
    optimal = client.get_optimal_trial("dartse")
    acc = [m for m in optimal["observation"]["metrics"] if m["name"] == "val_acc"][0]["latest"]
    assert acc > 0.5, acc  # discretized architecture fits the relu target
    tname = optimal["bestTrialName"]
    log = kcluster.logs(f"{tname}-worker-0")
    assert '"relu_linear", "relu_linear", "relu_linear", "relu_linear"' in log


# ---------------------------------------------------------------------- sobol


def test_sobol_stratification_and_bounds():
    """Sobol's defining property in base 2: for every dimension, the first
    2^k points (after the origin) land one-per-bin in a 2^k partition of
    [0,1) — far stronger balance than random search provides."""
    from kubeflow_tpu.katib.suggest.sobol import sobol_points
    import numpy as np

    from kubeflow_tpu.katib.suggest.sobol import MAX_DIMS

    dims = MAX_DIMS  # cover every table entry, incl. the last dimensions
    shift = np.zeros(dims, dtype=np.int64)
    for k in (3, 4, 6):
        n = 2 ** k
        pts = sobol_points(1, n, dims, shift)  # skip the origin like suggest()
        assert pts.shape == (n, dims) and (pts >= 0).all() and (pts < 1).all()
        full = sobol_points(0, n, dims, shift)
        assert (full >= 0).all() and (full < 1).all()
        for d in range(dims):
            # aligned block 0..2^k-1 hits every 2^k bin exactly once
            fbins = np.floor(full[:, d] * n).astype(int)
            assert sorted(fbins.tolist()) == list(range(n)), (d, k)


def test_sobol_suggester_resumes_and_respects_space():
    exp = make_exp_obj("sobol", settings={"random_state": "5"})
    sug = get_suggester("sobol")
    first = sug.suggest(exp, [], 4)
    assert len(first) == 4
    for a in first:
        assert 0.01 <= a["lr"] <= 1.0
        assert 8 <= a["units"] <= 64 and isinstance(a["units"], int)
        assert a["opt"] in ("sgd", "adam")
    # resuming after N trials continues the sequence, not restarts it
    fake = [fake_trial(a, 0.5) for a in first]
    second = sug.suggest(exp, fake, 4)
    assert all(s != f for s, f in zip(second, first))
    # deterministic for a given state + trial count
    assert sug.suggest(exp, fake, 4) == second


# ------------------------------------------------------------------------ pbt


def test_pbt_population_improves_over_generations():
    """Exploit/explore: over a few generations on a known objective
    (accuracy = 1-(lr-0.3)^2), the population's best and mean must improve
    on the random first generation, and children must stay in bounds."""
    exp = make_exp_obj("pbt", settings={"random_state": "3"})
    sug = get_suggester("pbt")

    def score(a):
        return 1.0 - (a["lr"] - 0.3) ** 2

    trials = []
    gen_best = []
    gen_mean = []
    for _ in range(4):
        batch = sug.suggest(exp, trials, 8)
        for a in batch:
            assert 0.01 <= a["lr"] <= 1.0
            assert 8 <= a["units"] <= 64
            assert a["opt"] in ("sgd", "adam")
        trials += [fake_trial(a, score(a)) for a in batch]
        gen_best.append(max(score(a) for a in batch))
        gen_mean.append(sum(score(a) for a in batch) / len(batch))
    assert gen_best[-1] >= gen_best[0]
    assert gen_mean[-1] > gen_mean[0]  # the POPULATION improves, not one child
    assert gen_best[-1] > 0.95  # converged near lr = 0.3


@pytest.mark.slow  # fast lane must stay under its 5-min budget (r1 #10)
def test_bare_pod_trial_experiment_succeeds(kcluster):
    """Bare-Pod trialTemplate (upstream's plain batch-job/pod trial): the
    pod IS the workload — completion tracked by pod phase, metrics read
    from the pod's own log, experiment reaches Succeeded with an optimal
    trial (katib-ui webui form's default trial spec uses this shape)."""
    import sys as _sys

    c = kcluster
    exp = experiment(
        "podtrial",
        [Parameter("lr", "double", min=0.1, max=0.9)],
        {"apiVersion": "v1", "kind": "Pod",
         "spec": {"restartPolicy": "Never", "containers": [{
             "name": "main",
             "command": [_sys.executable, "-u", "-c",
                         "print('accuracy=${trialParameters.lr}')"]}]}},
        objective_metric="accuracy", algorithm="random",
        max_trials=3, parallel_trials=2)
    c.api.create(exp)
    assert c.wait_for(
        lambda: has_condition(
            (c.api.try_get("Experiment", "podtrial") or {}).get("status", {}),
            kapi.SUCCEEDED),
        timeout=90)
    st = c.api.get("Experiment", "podtrial")["status"]
    opt = st["currentOptimalTrial"]
    assert opt["bestTrialName"]
    lr = float(opt["parameterAssignments"][0]["value"])
    # the objective was truly read from the pod log: it IS the lr value
    assert abs(float(opt["observation"]["metrics"][0]["latest"]) - lr) < 1e-9
