"""Mesh-sharded KV data plane tests (ISSUE 16): shard-native KVPG
frames, gather-free snapshot/scatter, and TP-honest serving — all on the
forced 8-device CPU mesh (conftest.py), in-process.

The headline contract: every KV movement path — session save/restore,
swap-preempt park, disaggregation handoff, fabric publish/pull — run at
tensor_parallel > 1 produces output BYTE-IDENTICAL to the TP=1 oracle,
moving only per-shard addressable bytes (engine_kv_shard_bytes_total);
a frame whose mesh degree matches the importer scatters shard-to-shard,
a mismatched degree reshards host-side as an EXPLICIT counted slow path
(engine_kv_reshard_total{outcome}), and every shard-level fault class
(torn / flipped / dropped single sub-frame) degrades exactly like
today's torn unified frame: byte-identical output, 0 leaked pages.
Degree-1 frames keep the version-1 wire layout byte for byte, so
pre-ISSUE-16 on-disk sessions and fabric frames still restore.
"""

import glob
import json
import os
import struct
import zlib

import jax
import numpy as np
import pytest

from kubeflow_tpu.serving.engine import Engine, EngineConfig, KVStoreConfig
from kubeflow_tpu.serving.engine import faults
from kubeflow_tpu.serving.engine import model as M
from kubeflow_tpu.serving.engine.faults import (FabricFaultConfig,
                                                FaultConfig,
                                                HandoffFaultConfig)
from kubeflow_tpu.serving.engine.kvstore import (FORMAT_VERSION, MAGIC,
                                                 SHARDED_FORMAT_VERSION,
                                                 KVStoreCorrupt, blob_degree,
                                                 pack_frame,
                                                 pack_sharded_frame,
                                                 reshard_blob, unpack_frame)
from kubeflow_tpu.serving.engine.perf import platform_peak_flops
from kubeflow_tpu.serving.engine.scheduler import SchedulerConfig
from kubeflow_tpu.serving.engine.serve import JetStreamModel
from kubeflow_tpu.serving.server import ModelServer

pytestmark = pytest.mark.sharded

# vocab >= 256 (byte tokenizer); 4 kv-heads so the pool shards at TP=2
# AND TP=4 on the 8-device host (TP=4 -> one kv-head per device)
CFG = M.DecoderConfig(vocab_size=288, d_model=32, n_layers=1, n_heads=4,
                      n_kv_heads=4, d_ff=64)
PAGE = 8
NUM_PAGES = 96
PROMPT_IDS = [(i * 13) % (CFG.vocab_size - 1) + 1 for i in range(20)]
TURN2_EXTRA = [5, 6, 7, 8, 9]
TURN3_EXTRA = [11, 12, 13]
PROMPT_TXT = "the quick brown fox jumps over the lazy dog"
SHARED = "You are a helpful assistant. Answer concisely and cite. " * 2


@pytest.fixture(scope="module")
def params():
    return M.init(jax.random.PRNGKey(0), CFG)


def _ec(**kw):
    base = dict(max_slots=2, page_size=PAGE, num_pages=NUM_PAGES,
                max_pages_per_slot=24)
    base.update(kw)
    return EngineConfig(**base)


def _leak(engine) -> int:
    s = engine.stats
    return (NUM_PAGES - 1) - s["free_pages"] - s["cached_pages"]


def _gen(model, prompt, mt, **params):
    return model.generate({"text_input": prompt,
                           "parameters": {"max_tokens": mt, **params}})


def _shard_bytes(engine, direction) -> float:
    return engine.telemetry.kv_shard_bytes.series().get(
        (("direction", direction),), 0.0)


def _reshard_count(engine, outcome) -> float:
    return engine.telemetry.kv_reshard.series().get(
        (("outcome", outcome),), 0.0)


def _degraded_handoffs(engine) -> float:
    return engine.telemetry.kv_handoff.series().get(
        (("outcome", "degraded"),), 0.0)


def _fabric_count(engine, outcome) -> float:
    return engine.telemetry.kv_fabric.series().get(
        (("outcome", outcome),), 0.0)


def _handoff_params(pre, source_port):
    return {"handoff": {"handle": (pre.get("handoff") or {}).get("handle"),
                        "source_port": source_port,
                        "token_ids": pre["token_ids"]}}


def _hint(engine, server):
    view = engine.fabric_view()
    assert view, "nothing published"
    return {"fabric": {"key": view[0]["key"], "source_port": server.port,
                       "pages": view[0]["pages"]}}


def _mk_shard_blobs(degree, heads=4, pages=3, quant=False, seed=0):
    """Per-shard (k, v) pytrees shaped like pool page snapshots
    [L, pages, heads/degree, page, hd], in kv-head order."""
    rng = np.random.default_rng(seed)
    per = heads // degree
    out = []
    for _ in range(degree):
        k = rng.standard_normal((1, pages, per, PAGE, 4)).astype(np.float32)
        v = rng.standard_normal((1, pages, per, PAGE, 4)).astype(np.float32)
        if quant:
            k = {"q": (k * 10).astype(np.int8),
                 "s": np.abs(rng.standard_normal(
                     (1, pages, per, PAGE, 1))).astype(np.float32)}
            v = {"q": (v * 10).astype(np.int8),
                 "s": np.abs(rng.standard_normal(
                     (1, pages, per, PAGE, 1))).astype(np.float32)}
        out.append((k, v))
    return out


def _tree_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ------------------------------------------------------- sharded frame units


def test_sharded_frame_roundtrip_and_header():
    blobs = _mk_shard_blobs(2)
    data, nbytes, crc = pack_sharded_frame(
        "handoff/1", blobs, {"resume_len": 9, "tp": 2})
    assert data[:4] == MAGIC
    assert struct.unpack("<I", data[4:8])[0] == SHARDED_FORMAT_VERSION
    out, header = unpack_frame(data)
    assert isinstance(out, list) and blob_degree(out) == 2
    _tree_equal(out, blobs)
    assert header["meta"]["tp"] == 2
    assert header["meta"]["resume_len"] == 9
    assert header["nbytes"] == nbytes == sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(blobs))
    assert len(header["shards"]) == 2
    assert crc == zlib.crc32(data[12 + struct.unpack(
        "<I", data[8:12])[0]:])
    # quantized ({"q","s"} pytree) shards survive the same framing
    qblobs = _mk_shard_blobs(4, quant=True)
    qdata, _, _ = pack_sharded_frame("fabric/abc", qblobs, {"pages": 3})
    qout, qheader = unpack_frame(qdata)
    assert blob_degree(qout) == 4
    _tree_equal(qout, qblobs)


def test_sharded_frame_shard_level_corruption_caught():
    """Per-shard integrity: a torn / flipped / zeroed single sub-frame
    fails ITS verifier with a shard-scoped error — the exact corruption
    the chaos plane (faults._corrupt_shard) injects on pulls — and an
    outer-stream truncation is caught by the shard-length table."""
    data, _, _ = pack_sharded_frame(
        "handoff/7", _mk_shard_blobs(2), {"resume_len": 4})
    regions = faults._shard_regions(data)
    assert len(regions) == 2
    # legacy v1 frames have no shard regions: shard chaos passes them by
    v1, _, _ = pack_frame("x", _mk_shard_blobs(1)[0], {})
    assert faults._shard_regions(v1) == []
    for kind in ("torn", "flip", "drop"):
        bad = faults._corrupt_shard(data, 1, kind == "torn", kind == "flip",
                                    kind == "drop")
        assert len(bad) == len(data), kind  # stream length intact
        with pytest.raises(KVStoreCorrupt, match="shard"):
            unpack_frame(bad)
    with pytest.raises(KVStoreCorrupt):
        unpack_frame(data[: len(data) - 5])  # torn outer stream
    with pytest.raises(KVStoreCorrupt):
        unpack_frame(data[: len(data) // 3])  # torn mid-table


def test_reshard_blob_exact_across_degrees():
    """Host-side resharding is exact: 4 -> 2 -> 1 -> 4 round-trips bit
    for bit (pure reindexing on the kv-head axis, no arithmetic), for
    plain and int8-quantized pools; a non-divisible degree refuses."""
    blobs4 = _mk_shard_blobs(4)
    uni = reshard_blob(blobs4, 1)
    assert blob_degree(uni) == 1 and isinstance(uni, tuple)
    assert uni[0].shape[2] == 4  # kv-head axis reassembled
    two = reshard_blob(uni, 2)
    assert blob_degree(two) == 2
    _tree_equal(reshard_blob(two, 4), blobs4)
    # quantized: q and s leaves both ride the kv-head axis
    q4 = _mk_shard_blobs(4, quant=True)
    _tree_equal(reshard_blob(reshard_blob(q4, 2), 4), q4)
    with pytest.raises(ValueError):
        reshard_blob(uni, 3)  # 4 kv-heads do not split 3 ways


def test_degree1_wire_format_byte_identical_to_legacy():
    """Satellite: the version-1 frame layout is pinned BYTE FOR BYTE
    against a hand-assembled legacy frame — pre-ISSUE-16 on-disk session
    page files and fabric frames must keep restoring, and degree-1
    engines must keep writing bytes a pre-ISSUE-16 reader can verify."""
    rng = np.random.default_rng(3)
    k = rng.standard_normal((1, 2, 4, PAGE, 4)).astype(np.float32)
    v = rng.standard_normal((1, 2, 4, PAGE, 4)).astype(np.float32)
    meta = {"resume_len": 9, "page_size": PAGE}
    # the legacy layout, assembled by hand exactly as the pre-ISSUE-16
    # writer did: magic | u32 1 | u32 header_len | header JSON | payload
    spec = {"t": "t", "v": [
        {"t": "a", "dtype": "float32", "shape": list(k.shape), "i": 0},
        {"t": "a", "dtype": "float32", "shape": list(v.shape), "i": 1}]}
    payload = k.tobytes() + v.tobytes()
    crc = zlib.crc32(v.tobytes(), zlib.crc32(k.tobytes()))
    header = json.dumps({
        "v": 1, "key": "session/s/3", "spec": spec, "meta": meta,
        "nbytes": len(payload), "crc": crc, "version": 1}).encode()
    legacy = (MAGIC + struct.pack("<II", 1, len(header)) + header + payload)
    assert FORMAT_VERSION == 1
    data, nbytes, _ = pack_frame("session/s/3", (k, v), meta)
    assert data == legacy  # byte-for-byte
    blob, hdr = unpack_frame(legacy)  # and old bytes still restore
    _tree_equal(blob, (k, v))
    assert hdr["meta"] == meta and hdr["nbytes"] == nbytes


def test_tp1_session_disk_frames_stay_legacy(params, tmp_path):
    """A degree-1 engine's durable session writes version-1 page files
    with no "tp" meta key — bytes a pre-ISSUE-16 engine restores."""
    eng = Engine(params, CFG, _ec(max_slots=4, kv_store=KVStoreConfig(
        host_max_bytes=0, disk_dir=str(tmp_path / "kv"))))
    eng.start()
    try:
        r1 = eng.generate(PROMPT_IDS, 10, session_id="s")
        assert r1["session"]["durable"]
    finally:
        eng.stop()
    files = glob.glob(str(tmp_path / "kv" / "**" / "*.kvpg"),
                      recursive=True)
    assert files
    for path in files:
        with open(path, "rb") as f:
            raw = f.read()
        assert raw[:4] == MAGIC
        assert struct.unpack("<I", raw[4:8])[0] == FORMAT_VERSION
        _, header = unpack_frame(raw)
        assert "tp" not in header["meta"], path


# --------------------------------------------- TP sessions: save/restore


@pytest.fixture(scope="module")
def cold(params):
    """The TP=1 uninterrupted oracle: each turn run cold on a plain
    engine — the byte-identity reference for every TP degree below."""
    eng = Engine(params, CFG, _ec(max_slots=4))
    eng.start()
    try:
        r1 = eng.generate(PROMPT_IDS, 10)
        ctx2 = PROMPT_IDS + r1["tokens"] + TURN2_EXTRA
        r2 = eng.generate(ctx2, 10)
        ctx3 = ctx2 + r2["tokens"] + TURN3_EXTRA
        r3 = eng.generate(ctx3, 10)
        return {"t1": r1["tokens"], "ctx2": ctx2, "t2": r2["tokens"],
                "ctx3": ctx3, "t3": r3["tokens"]}
    finally:
        eng.stop()


def _leaked(eng) -> int:
    s = eng.stats
    return (eng.ec.num_pages - 1) - s["free_pages"] - s["cached_pages"]


@pytest.mark.parametrize("tp,depth", [(2, 0), (2, 1), (4, 1)])
def test_tp_session_save_restore_byte_identical(params, cold, tmp_path,
                                                tp, depth):
    """Session turns at TP>1 — pin snapshots each shard's OWN pages
    (engine_kv_shard_bytes_total{direction="export"}), the warm turn
    scatters shard-to-shard — emit the TP=1 oracle's exact bytes at
    pipeline depth 0 and 1, with 0 leaked pages."""
    eng = Engine(params, CFG, _ec(
        max_slots=4, tensor_parallel=tp, pipeline_depth=depth,
        kv_store=KVStoreConfig(disk_dir=str(tmp_path / "kv"))))
    eng.start()
    try:
        r1 = eng.generate(PROMPT_IDS, 10, session_id="s")
        assert r1["tokens"] == cold["t1"]
        assert r1["session"]["pinned"] and r1["session"]["durable"]
        r2 = eng.generate(cold["ctx2"], 10, session_id="s")
        assert r2["tokens"] == cold["t2"]  # byte-identical to TP=1 cold
        assert r2["session"]["restore"] == "host"
        assert _shard_bytes(eng, "export") > 0
        assert _shard_bytes(eng, "restore") > 0
        # matching degree never pays the reshard slow path
        assert _reshard_count(eng, "reshard") == 0
        assert _reshard_count(eng, "match") >= 1
        assert _leaked(eng) == 0
    finally:
        eng.stop()


def test_cross_degree_session_restart_resharded(params, cold, tmp_path):
    """A session pinned at TP=2 restores on a TP=4 restart and again on
    a plain unified restart — byte-identically, through the EXPLICIT
    counted host-side reshard (engine_kv_reshard_total{outcome=
    "reshard"}), never silent garbage."""
    kv = KVStoreConfig(disk_dir=str(tmp_path / "kv"))
    e1 = Engine(params, CFG, _ec(max_slots=4, tensor_parallel=2,
                                 kv_store=kv))
    e1.start()
    try:
        r1 = e1.generate(PROMPT_IDS, 10, session_id="s")
        assert r1["tokens"] == cold["t1"] and r1["session"]["durable"]
    finally:
        e1.stop()
    # the durable frame records its degree; list blobs persist natively
    files = glob.glob(str(tmp_path / "kv" / "**" / "*.kvpg"),
                      recursive=True)
    metas = []
    for path in files:
        with open(path, "rb") as f:
            metas.append(unpack_frame(f.read())[1]["meta"])
    assert any(m.get("tp") == 2 for m in metas)

    e2 = Engine(params, CFG, _ec(max_slots=4, tensor_parallel=4,
                                 kv_store=kv))
    assert "s" in e2.sessions()  # manifest replayed before any touch
    e2.start()
    try:
        r2 = e2.generate(cold["ctx2"], 10, session_id="s")
        assert r2["tokens"] == cold["t2"]
        assert r2["session"]["restore"] == "disk"
        assert _reshard_count(e2, "reshard") >= 1
        assert _leaked(e2) == 0
    finally:
        e2.stop()

    e3 = Engine(params, CFG, _ec(max_slots=4, kv_store=kv))  # unified
    e3.start()
    try:
        r3 = e3.generate(cold["ctx3"], 10, session_id="s")
        assert r3["tokens"] == cold["t3"]
        assert r3["session"]["restore"] == "disk"
        assert _reshard_count(e3, "reshard") >= 1
        assert _leaked(e3) == 0
    finally:
        e3.stop()


def test_tp_swap_preempt_byte_identical_zero_leaks(params):
    """Chaos preemption storm at TP=2 with forced swap: every parked
    blob is a per-shard snapshot (no gathered pool on host), every
    resume scatters shard-to-shard, and every request's bytes match the
    calm TP=1 run — swap store drained, 0 leaked pages."""
    prompts = [[(i * 7 + j * 13) % (CFG.vocab_size - 1) + 1
                for j in range(6 + i)] for i in range(4)]

    def run_all(eng):
        futs = [eng.generate_async(p, 20, priority="batch")
                for p in prompts]
        return [f.result(timeout=300) for f in futs]

    eng = Engine(params, CFG, _ec(max_slots=4))
    eng.start()
    try:
        baseline = run_all(eng)
    finally:
        eng.stop()

    eng = Engine(params, CFG, _ec(
        max_slots=4, tensor_parallel=2,
        chaos=FaultConfig(preempt_every=5),
        scheduler=SchedulerConfig(swap_policy="swap", swap_min_tokens=8)))
    eng.start()
    try:
        stormed = run_all(eng)
        for base, got in zip(baseline, stormed):
            assert got["tokens"] == base["tokens"]  # byte-identical
        s = eng.stats
        assert s["preemptions"] > 0 and s["swapped_out"] > 0
        assert s["swapped_in"] == s["swapped_out"]
        assert s["swap_used_bytes"] == 0  # every parked blob restored
        assert _shard_bytes(eng, "export") > 0
        assert _shard_bytes(eng, "restore") > 0
        assert _leaked(eng) == 0
    finally:
        eng.stop()


# -------------------------------------------------- TP handoff (disagg)


def _pair(params, ptp, dtp, prefill_chaos=None, decode_chaos=None, **dkw):
    ep = Engine(params, CFG, _ec(role="prefill", tensor_parallel=ptp,
                                 handoff_chaos=prefill_chaos))
    sp = ModelServer([JetStreamModel("m", "", engine=ep)], port=0)
    sp.start()
    ed = Engine(params, CFG, _ec(role="decode", tensor_parallel=dtp,
                                 handoff_chaos=decode_chaos, **dkw))
    ed.start()
    md = JetStreamModel("m", "", engine=ed)
    return ep, sp, ed, md


def test_tp_handoff_cross_degree_byte_identity(params):
    """Prefill->decode handoff across mesh degrees: TP=2 -> TP=2 imports
    shard-to-shard ("match"); TP=2 -> unified, unified -> TP=2 and
    TP=2 -> TP=4 reshard host-side (counted) — every combination
    byte-identical to the unified TP=1 oracle, with the decode replica
    never re-prefilling and 0 leaked pages on both sides."""
    eu = Engine(params, CFG, _ec())
    eu.start()
    mu = JetStreamModel("m", "", engine=eu)
    try:
        ref = _gen(mu, PROMPT_TXT, 12)
        # (prefill tp, decode tp, expected import outcome); the matching
        # pair also runs at pipeline depth 0 — the sync scheduler drives
        # the same scatter
        cases = [(2, 2, "match", {"pipeline_depth": 0}),
                 (2, 2, "match", {}),
                 (2, 1, "reshard", {}),
                 (1, 2, "reshard", {}),
                 (2, 4, "reshard", {})]
        for ptp, dtp, outcome, dkw in cases:
            tag = (ptp, dtp, dkw)
            ep, sp, ed, md = _pair(params, ptp, dtp, **dkw)
            try:
                pre = _gen(sp.models["m"], PROMPT_TXT, 12, kv_handoff=True)
                assert pre["handoff"].get("handle"), tag
                out = _gen(md, PROMPT_TXT, 12,
                           **_handoff_params(pre, sp.port))
                assert out["token_ids"] == ref["token_ids"], tag
                assert out["text_output"] == ref["text_output"], tag
                assert ed.stats["prefill_dispatches"] == 0, \
                    f"{tag}: decode replica re-prefilled"
                assert _reshard_count(ed, outcome) >= 1, tag
                if ptp > 1:  # export moved per-shard bytes only
                    assert _shard_bytes(ep, "export") > 0, tag
                assert _leak(ep) == 0 and _leak(ed) == 0, tag
            finally:
                sp.stop()
                ep.stop(drain=False)
                ed.stop(drain=False)
    finally:
        eu.stop(drain=False)


def test_shard_chaos_handoff_degrades_with_zero_leaks(params):
    """A torn / flipped / dropped SINGLE sub-frame on the handoff pull
    degrades exactly like a torn unified frame: re-prefill, byte-
    identical output, degradation counted, 0 leaked pages on BOTH
    replicas."""
    eu = Engine(params, CFG, _ec())
    eu.start()
    mu = JetStreamModel("m", "", engine=eu)
    try:
        ref = _gen(mu, PROMPT_TXT, 10)
        cases = {
            "shard_torn": HandoffFaultConfig(shard_torn_pull_on=1),
            "shard_flip": HandoffFaultConfig(shard_flip_pull_on=1),
            "shard_drop": HandoffFaultConfig(shard_drop_pull_on=1),
        }
        for name, chaos in cases.items():
            ep, sp, ed, md = _pair(params, 2, 2, decode_chaos=chaos)
            try:
                pre = _gen(sp.models["m"], PROMPT_TXT, 10, kv_handoff=True)
                out = _gen(md, PROMPT_TXT, 10,
                           **_handoff_params(pre, sp.port))
                assert out["token_ids"] == ref["token_ids"], name
                assert out["tokens"] == 10, name
                assert _degraded_handoffs(ed) >= 1, name
                assert ed._handoff_chaos.stats()[
                    "injected_shard_faults"] >= 1, name
                assert _leak(ep) == 0 and _leak(ed) == 0, name
            finally:
                sp.stop()
                ep.stop(drain=False)
                ed.stop(drain=False)
    finally:
        eu.stop(drain=False)


# ----------------------------------------------------- TP fabric pulls


def test_tp_fabric_publish_pull_cross_degree(params):
    """A prefix published by a TP=2 replica (per-shard snapshot, no
    gathered pool) fault-in on TP=2, TP=4 and unified pullers — each
    byte-identical to the TP=1 cold oracle, matching degree scattering
    shard-to-shard, mismatched degrees through the counted reshard."""
    eu = Engine(params, CFG, _ec(fabric=False))
    eu.start()
    mu = JetStreamModel("m", "", engine=eu)
    prompt = SHARED + "Q?"
    ref = _gen(mu, prompt, 12)
    ea = Engine(params, CFG, _ec(fabric=True, tensor_parallel=2))
    sa = ModelServer([JetStreamModel("m", "", engine=ea)], port=0)
    sa.start()
    try:
        first = _gen(sa.models["m"], prompt, 12)
        assert first["token_ids"] == ref["token_ids"]
        assert ea.stats["fabric"]["publishes"] == 1
        assert _shard_bytes(ea, "export") > 0
        for dtp, outcome in ((2, "match"), (4, "reshard"), (1, "reshard")):
            eb = Engine(params, CFG, _ec(fabric=True, tensor_parallel=dtp))
            eb.start()
            mb = JetStreamModel("m", "", engine=eb)
            try:
                out = _gen(mb, prompt, 12, **_hint(ea, sa))
                assert out["token_ids"] == ref["token_ids"], dtp
                assert out["fabric"] == {"restore": "hit"}, dtp
                assert _fabric_count(eb, "hit") == 1, dtp
                assert _reshard_count(eb, outcome) >= 1, dtp
                assert _leak(eb) == 0, dtp
            finally:
                eb.stop(drain=False)
        assert ea.stats["fabric"]["pulls"] == 3
        assert _leak(ea) == 0
    finally:
        sa.stop()
        ea.stop(drain=False)
        eu.stop(drain=False)


def test_shard_chaos_fabric_degrades_with_zero_leaks(params):
    """Shard-level corruption on the fabric pull degrades to plain
    re-prefill: byte-identical output, engine_kv_fabric_total{outcome=
    "degraded"}, no hit, 0 leaked pages on both replicas."""
    eu = Engine(params, CFG, _ec(fabric=False))
    eu.start()
    mu = JetStreamModel("m", "", engine=eu)
    prompt = SHARED + "Q?"
    ref = _gen(mu, prompt, 10)
    try:
        cases = {
            "shard_torn": FabricFaultConfig(shard_torn_pull_on=1),
            "shard_flip": FabricFaultConfig(shard_flip_pull_on=1),
            "shard_drop": FabricFaultConfig(shard_drop_pull_on=1),
        }
        for name, chaos in cases.items():
            ea = Engine(params, CFG, _ec(fabric=True, tensor_parallel=2))
            sa = ModelServer([JetStreamModel("m", "", engine=ea)], port=0)
            sa.start()
            eb = Engine(params, CFG, _ec(fabric=True, tensor_parallel=2,
                                         fabric_chaos=chaos))
            eb.start()
            mb = JetStreamModel("m", "", engine=eb)
            try:
                _gen(sa.models["m"], prompt, 10)
                out = _gen(mb, prompt, 10, **_hint(ea, sa))
                assert out["token_ids"] == ref["token_ids"], name
                assert out["fabric"] == {"restore": "degraded"}, (name, out)
                assert _fabric_count(eb, "degraded") >= 1, name
                assert _fabric_count(eb, "hit") == 0, name
                assert eb._fabric_chaos.stats()[
                    "injected_shard_faults"] >= 1, name
                assert _leak(ea) == 0 and _leak(eb) == 0, name
            finally:
                sa.stop()
                ea.stop(drain=False)
                eb.stop(drain=False)
    finally:
        eu.stop(drain=False)


# ------------------------------------------------- config surface + MFU


def test_engine_json_tensor_parallel_validation(tmp_path):
    """engine.json tensor_parallel misconfigurations fail at load with a
    message naming the FILE and the constraint (the role/speculative
    validation pattern) — including refusing to silently serve at a
    lower degree than requested."""
    base_cfg = {"vocab_size": 64, "d_model": 32, "n_layers": 1,
                "n_heads": 4, "n_kv_heads": 4, "d_ff": 64}
    base_ec = {"max_slots": 2, "num_pages": 32, "page_size": 8}

    def mk(name, cfgj, ecj):
        d = tmp_path / name
        d.mkdir()
        (d / "config.json").write_text(json.dumps(cfgj))
        (d / "engine.json").write_text(json.dumps(ecj))
        return str(d)

    cases = [
        ("zero", base_cfg, {**base_ec, "tensor_parallel": 0},
         r"engine\.json: tensor_parallel=0 must be an integer >= 1"),
        ("heads", base_cfg, {**base_ec, "tensor_parallel": 3},
         r"tensor_parallel=3 must divide n_heads=4 and n_kv_heads=4"),
        ("dff", {**base_cfg, "d_ff": 66}, {**base_ec, "tensor_parallel": 4},
         r"tensor_parallel=4 must divide d_ff=66"),
        ("devices", {**base_cfg, "n_heads": 16, "n_kv_heads": 16,
                     "d_model": 64},
         {**base_ec, "tensor_parallel": 16},
         r"needs 16 devices, have \d+ — refusing to silently serve"),
    ]
    for name, cfgj, ecj, pattern in cases:
        m = JetStreamModel("t", mk(name, cfgj, ecj))
        with pytest.raises(ValueError, match=pattern):
            m.load()
    # and a valid degree really serves sharded
    m = JetStreamModel("t", mk("good", base_cfg,
                               {**base_ec, "tensor_parallel": 2}))
    m.load()
    try:
        assert m.engine._mesh is not None
        assert m.engine.ec.tensor_parallel == 2
    finally:
        m.engine.stop()


def test_per_mesh_peak_flops_label_and_honesty():
    """TP-honest MFU denominators: a TP=N TPU engine charges against N
    chips' peak (N chips really are N× the silicon) under an xN-suffixed
    label; the CPU fallback keeps the HOST-wide estimate un-multiplied —
    the forced multi-device CPU mesh is virtual — but still annotates
    the degree so per-mesh rows stay distinguishable."""
    l1, f1 = platform_peak_flops("cpu", "", 1)
    l4, f4 = platform_peak_flops("cpu", "", 4)
    assert l4 == l1 + "x4"
    assert f4 == f1  # virtual devices share the same cores
    t1, p1 = platform_peak_flops("tpu", "TPU v5e", 1)
    t4, p4 = platform_peak_flops("tpu", "TPU v5e", 4)
    assert t1 == "tpu-v5e" and t4 == "tpu-v5ex4"
    assert p4 == 4 * p1
