"""Latency attribution plane tests (ISSUE 18): the waterfall assembler.

The load-bearing invariant everywhere: **segment sum == wall by
construction** — on every request shape (clean, chunked prefill, spec
verify, preempt/resume, failover retry, fabric/handoff pulls, shed,
failed), any remainder lands in an explicit ``unaccounted`` segment and
is bounded.  Clock-offset estimation is unit-tested with explicit
clocks including negative skew; the critical path subtracts overlapped
work; the fleet waterfall and the per-class budget endpoint run through
the real service proxy; and the plane costs nothing when telemetry is
off.
"""

import json
import urllib.error
import urllib.request

import jax
import pytest

from kubeflow_tpu.core.api import APIServer
from kubeflow_tpu.serving import waterfall as wf
from kubeflow_tpu.serving.api import LABEL_ISVC
from kubeflow_tpu.serving.controllers import (POD_PORT_ANNOTATION,
                                              PROXY_PORT_ANNOTATION)
from kubeflow_tpu.serving.engine import Engine, EngineConfig
from kubeflow_tpu.serving.engine import model as M
from kubeflow_tpu.serving.engine.serve import JetStreamModel
from kubeflow_tpu.serving.router import (RELAY_TIMEOUT_ANNOTATION,
                                         ServiceProxy)
from kubeflow_tpu.serving.server import ModelServer
from kubeflow_tpu.utils.net import find_free_ports

pytestmark = pytest.mark.waterfall

# vocab >= 256: the JetStream byte tokenizer addresses ids 0..255
CFG = M.DecoderConfig(vocab_size=288, d_model=32, n_layers=1, n_heads=2,
                      n_kv_heads=1, d_ff=64)


@pytest.fixture(scope="module")
def params():
    return M.init(jax.random.PRNGKey(0), CFG)


def _ec(**kw):
    base = dict(max_slots=4, num_pages=96, page_size=8,
                max_pages_per_slot=24)
    base.update(kw)
    return EngineConfig(**base)


def _sum_ok(out, tol=1e-6):
    """The invariant, asserted everywhere: segments partition the wall."""
    total = sum(s["dur_s"] for s in out["segments"])
    assert abs(total - out["wall_s"]) < tol, (total, out["wall_s"])
    assert all(s["dur_s"] >= 0 for s in out["segments"])


def _span(events, rid=7, cls="interactive", hints=None, **extra):
    """Synthetic engine RequestSpan.to_dict shape from (phase, t) pairs."""
    out = {"rid": rid, "component": "engine", "trace_id": "t" * 32,
           "span_id": "s" * 16, "parent_id": None, "cls": cls,
           "outcome": next((p for p, _ in events
                            if p in ("done", "shed", "failed", "cancelled")),
                           None),
           "events": [{"phase": p, "t_s": t} for p, t in events]}
    if hints:
        out["hints"] = dict(hints)
    out.update(extra)
    return out


# ------------------------------------------------------------- seal units


def test_seal_partitions_gaps_overlaps_and_clips():
    segs, over = wf.seal([(0.0, 0.1, "a", None),      # clean
                          (0.05, 0.2, "b", None),     # overlaps a's tail
                          (0.3, 0.4, "c", None),      # gap before
                          (0.35, 0.38, "d", None),    # fully inside c
                          (0.9, 1.5, "e", None)],     # clipped at wall
                         1.0)
    assert abs(sum(s["dur_s"] for s in segs) - 1.0) < 1e-12
    # a kept, b's overlap clipped, the two gaps became explicit
    # unaccounted segments, d fully swallowed, e clipped at the wall
    assert [s["name"] for s in segs] == [
        "a", "b", "unaccounted", "c", "unaccounted", "e"]
    # clipped parts are reported as overlapped work, not dropped
    reasons = {o["name"]: o["reason"] for o in over}
    assert reasons["b"] == "overlap" and reasons["d"] == "overlap"


def test_seal_empty_and_zero_wall():
    segs, over = wf.seal([], 0.5)
    assert segs == [{"name": "unaccounted", "start_s": 0.0, "dur_s": 0.5}]
    assert over == []
    segs, over = wf.seal([(0.0, 0.1, "a", None)], 0.0)
    assert sum(s["dur_s"] for s in segs) == 0.0


def test_seal_last_seam_closes_exactly():
    # float noise at the tail must not leave a dangling sliver
    segs, _ = wf.seal([(0.0, 0.3333333333, "a", None),
                       (0.3333333333, 0.9999999999, "b", None)], 1.0)
    assert abs(sum(s["dur_s"] for s in segs) - 1.0) < 1e-12


# ------------------------------------------- engine partition, every shape


CLEAN = [("queued", 0.0), ("admitted", 0.01), ("prefill", 0.05),
         ("first_token", 0.06), ("done", 0.2)]
CHUNKED = [("queued", 0.0), ("admitted", 0.02), ("prefill", 0.05),
           ("prefill", 0.09), ("prefill", 0.12), ("first_token", 0.13),
           ("done", 0.3)]
PREEMPT = [("queued", 0.0), ("admitted", 0.01), ("prefill", 0.04),
           ("first_token", 0.05), ("preempted", 0.1), ("readmitted", 0.15),
           ("resumed", 0.17), ("done", 0.3)]
SHED = [("queued", 0.0), ("shed", 0.08)]
FAILED = [("queued", 0.0), ("admitted", 0.01), ("prefill", 0.05),
          ("failed", 0.07)]
CANCELLED = [("queued", 0.0), ("admitted", 0.01), ("prefill", 0.03),
             ("first_token", 0.04), ("cancelled", 0.09)]
FABRIC = [("queued", 0.0), ("admitted", 0.01), ("fabric_restore", 0.06),
          ("prefill", 0.09), ("first_token", 0.1), ("done", 0.2)]
HANDOFF = [("queued", 0.0), ("admitted", 0.01), ("handoff_import", 0.05),
           ("first_token", 0.06), ("done", 0.15)]
SESSION = [("queued", 0.0), ("admitted", 0.01), ("session_restore", 0.04),
           ("prefill", 0.07), ("first_token", 0.08), ("done", 0.2)]


@pytest.mark.parametrize("events,expect", [
    (CLEAN, {"engine_queue", "prefill", "decode"}),
    (CHUNKED, {"engine_queue", "prefill", "decode"}),
    (PREEMPT, {"engine_queue", "prefill", "decode", "preempt_restore"}),
    (SHED, {"engine_queue"}),
    (FAILED, {"engine_queue", "prefill"}),
    (CANCELLED, {"engine_queue", "prefill", "decode"}),
    (FABRIC, {"engine_queue", "fabric_pull", "prefill", "decode"}),
    (HANDOFF, {"engine_queue", "handoff_import", "decode"}),
    (SESSION, {"engine_queue", "session_restore", "prefill", "decode"}),
])
def test_engine_waterfall_sum_equals_wall_every_shape(events, expect):
    out = wf.build_engine_waterfall(_span(events))
    _sum_ok(out, tol=1e-9)
    assert out["wall_s"] == events[-1][1]
    names = {s["name"] for s in out["segments"]}
    assert expect <= names, (expect, names)
    # the engine partition is contiguous by construction: no gaps
    assert out["unaccounted_s"] == 0.0
    # every emitted segment name is in the documented glossary
    assert names <= set(wf.SEGMENTS)


def test_chunked_prefill_gets_per_chunk_segments():
    out = wf.build_engine_waterfall(_span(CHUNKED))
    chunks = [s for s in out["segments"] if s["name"] == "prefill"]
    # three dispatched chunks + the chunk ending at first_token
    assert [c.get("chunk") for c in chunks] == [0, 1, 2, 3]


def test_spec_verify_carved_from_decode_keeps_partition_exact():
    span = _span(CLEAN, hints={"verify": 0.05})
    out = wf.build_engine_waterfall(span)
    _sum_ok(out, tol=1e-9)
    t = out["totals"]
    assert abs(t["spec_verify"] - 0.05) < 1e-9
    # carve came OUT of decode: decode + verify == the original gap
    assert abs(t["decode"] + t["spec_verify"] - 0.14) < 1e-9
    # oversized hint is clamped: the partition can never exceed the wall
    out2 = wf.build_engine_waterfall(_span(CLEAN, hints={"verify": 99.0}))
    _sum_ok(out2, tol=1e-9)
    assert out2["totals"]["spec_verify"] <= 0.14 + 1e-9


def test_pre_submit_pull_hints_ride_alongside_not_inside():
    span = _span(CLEAN, hints={"pre_fabric_pull": 0.02})
    out = wf.build_engine_waterfall(span)
    _sum_ok(out, tol=1e-9)  # the engine axis is untouched
    assert out["pre_s"] == {"fabric_pull": 0.02}


def test_non_monotonic_marks_clamp_never_negative():
    events = [("queued", 0.0), ("admitted", 0.05), ("prefill", 0.04),
              ("done", 0.1)]
    out = wf.build_engine_waterfall(_span(events))
    _sum_ok(out, tol=1e-9)


# ------------------------------------------------------------ clock offset


def test_clock_offset_bracketing_regime():
    # hop [10.0, 10.5] brackets an 0.4 s engine span: the 0.1 s residual
    # splits evenly, so engine zero sits at 10.05 on the ingress clock
    off, residual = wf.estimate_offset(10.0, 0.5, 0.4)
    assert abs(off - 10.05) < 1e-12
    assert abs(residual - 0.1) < 1e-12


def test_clock_offset_negative_skew():
    # engine reports MORE wall than the hop observed (clock drift or an
    # early hop close): pin to hop start, surface the negative residual
    off, residual = wf.estimate_offset(10.0, 0.3, 0.4)
    assert off == 10.0
    assert residual < 0 and abs(residual + 0.1) < 1e-12


def test_fleet_waterfall_negative_skew_still_partitions():
    root = {"component": "ingress", "name": "request", "trace_id": "t",
            "span_id": "r", "parent_id": None, "status": 200,
            "t_start_s": 0.0, "duration_s": 0.3,
            "pre_s": {"ingress_parse": 0.001, "admission": 0.002}}
    hop = {"component": "ingress", "name": "relay_attempt", "trace_id": "t",
           "span_id": "h1", "parent_id": "r", "outcome": "ok",
           "backend": 9000, "kind": "relay",
           "t_start_s": 0.0, "duration_s": 0.25}
    eng = _span([("queued", 0.0), ("admitted", 0.01), ("prefill", 0.1),
                 ("first_token", 0.12), ("done", 0.4)],  # wall > hop dur
                parent_id="h1")
    out = wf.build_fleet_waterfall(
        {"trace_id": "t", "spans": [root, hop, eng]})
    _sum_ok(out)
    assert out["clock_offsets"]["9000"]["residual_s"] < 0
    # the overrun was clipped into overlapped work, not silently absorbed
    assert any(o["reason"] in ("overlap", "beyond_wall")
               for o in out.get("overlapped", ()))


# ---------------------------------------------------------- critical path


def test_critical_path_subtracts_overlapped_decode_work():
    segs, _ = wf.seal([(0.0, 0.2, "prefill", None),
                       (0.2, 1.0, "decode", None)], 1.0)
    overlays = [{"name": "pipeline_drain", "start_s": 0.3, "dur_s": 0.1},
                {"name": "pipeline_readback", "start_s": 0.35,
                 "dur_s": 0.15}]  # merged union: [0.3, 0.5] -> 0.2 hidden
    cp = wf.critical_path(segs, overlays, 1.0)
    assert abs(cp["hidden_s"] - 0.2) < 1e-9
    assert abs(cp["critical_path_s"] - 0.8) < 1e-9
    assert cp["path"] == ["prefill", "decode"]


def test_critical_path_without_overlap_is_the_wall():
    segs, _ = wf.seal([(0.0, 1.0, "decode", None)], 1.0)
    cp = wf.critical_path(segs, [], 1.0)
    assert cp["critical_path_s"] == 1.0 and cp["hidden_s"] == 0.0


def test_overlays_from_timeline_windows_and_converts_clock():
    records = [{"tick": 1, "t_s": 100.5,
                "segments": {"drain": 0.01, "readback": 0.02,
                             "dispatch": 0.5}},      # dispatch: not overlap
               {"tick": 2, "t_s": 200.0,
                "segments": {"drain": 0.01}}]        # outside the window
    out = wf.overlays_from_timeline(records, t0=100.0, t_end=101.0)
    assert [o["name"] for o in out] == ["pipeline_drain",
                                       "pipeline_readback"]
    assert out[0]["start_s"] == 0.5  # absolute 100.5 -> span-relative


# ------------------------------------------------- trace hygiene (fleet)


def test_dedupe_spans_on_trace_and_span_id():
    a = {"trace_id": "t", "span_id": "x", "v": 1}
    b = {"trace_id": "t", "span_id": "x", "v": 2}   # double-scraped copy
    c = {"trace_id": "t", "span_id": "y"}
    d = {"trace_id": "t", "span_id": None}          # id-less: always kept
    out = wf.dedupe_spans([a, b, c, d, dict(d)])
    assert [s.get("span_id") for s in out] == ["x", "y", None, None]
    assert out[0]["v"] == 1  # first occurrence wins


def test_order_spans_causal_across_skewed_replicas():
    hop1 = {"component": "ingress", "name": "relay_attempt", "span_id": "h1",
            "t_start_s": 0.0, "duration_s": 0.1, "outcome": "connect"}
    hop2 = {"component": "ingress", "name": "relay_attempt", "span_id": "h2",
            "t_start_s": 0.15, "duration_s": 0.3, "outcome": "ok"}
    e2 = _span([("queued", 0.0), ("done", 0.2)], parent_id="h2")
    e1 = _span([("queued", 0.0), ("done", 0.05)], parent_id="h1")
    root = {"component": "ingress", "name": "request", "span_id": "r",
            "t_start_s": 0.0, "duration_s": 0.5}
    # scrape order: second replica's span first
    out = wf.order_spans([e2, hop2, e1, hop1, root])
    engine_order = [s["parent_id"] for s in out
                    if s.get("component") == "engine"]
    assert engine_order == ["h1", "h2"]  # causal, not scrape, order
    adj = {s["parent_id"]: s["t_start_adj_s"] for s in out
           if s.get("component") == "engine"}
    # each engine zero lands inside its parent hop's bracket
    assert 0.0 <= adj["h1"] <= 0.1
    assert 0.15 <= adj["h2"] <= 0.45


# --------------------------------------------------- fleet waterfall units


def _failover_trace():
    root = {"component": "ingress", "name": "request", "trace_id": "t",
            "span_id": "r", "parent_id": None, "status": 200,
            "t_start_s": 0.0, "duration_s": 1.0,
            "pre_s": {"ingress_parse": 0.004, "admission": 0.006}}
    dead = {"component": "ingress", "name": "relay_attempt", "trace_id": "t",
            "span_id": "h1", "parent_id": "r", "outcome": "connect",
            "error": "boom", "backend": 9000, "kind": "relay",
            "t_start_s": 0.01, "duration_s": 0.1}
    ok = {"component": "ingress", "name": "relay_attempt", "trace_id": "t",
          "span_id": "h2", "parent_id": "r", "outcome": "ok",
          "backend": 9001, "kind": "relay",
          "t_start_s": 0.2, "duration_s": 0.7}
    eng = _span([("queued", 0.0), ("admitted", 0.02), ("prefill", 0.2),
                 ("first_token", 0.22), ("done", 0.6)], parent_id="h2",
                replica="fleet-1", hints={"pre_fabric_pull": 0.01})
    return {"trace_id": "t", "spans": [root, dead, ok, eng]}


def test_fleet_waterfall_failover_shape():
    out = wf.build_fleet_waterfall(_failover_trace())
    _sum_ok(out)
    assert abs(out["wall_s"] - 1.01) < 1e-9  # pre_s + root duration
    t = out["totals"]
    assert abs(t["ingress_parse"] - 0.004) < 1e-9
    assert abs(t["admission"] - 0.006) < 1e-9
    # the dead attempt is explicit failover wall; the backoff between the
    # attempts is an explicit retry_gap
    assert abs(t["failover"] - 0.1) < 1e-9
    assert abs(t["retry_gap"] - 0.09) < 1e-9
    # engine sub-segments are placed on the ingress axis, marked skewed
    eng_segs = [s for s in out["segments"] if s.get("skew_adjusted")]
    assert eng_segs and {"engine_queue", "prefill",
                         "decode"} <= {s["name"] for s in eng_segs}
    # the serve-layer pull hint was carved out of the hop lead-in
    assert any(s["name"] == "fabric_pull" and s.get("pre_submit")
               for s in out["segments"])
    # per-backend clock evidence rides the waterfall
    assert out["clock_offsets"]["fleet-1"]["residual_s"] > 0
    assert out["attempts"] == 2
    # proxy overhead = wall minus every engine-attributed second
    assert abs(out["proxy_overhead_s"] - (1.01 - 0.6)) < 1e-6
    assert out["unaccounted_s"] < 0.05 * out["wall_s"]


def test_fleet_waterfall_opaque_hop_and_missing_root():
    assert wf.build_fleet_waterfall({"spans": []}) is None
    # a successful hop with no engine span stays honest: relay_backend
    spans = [{"component": "ingress", "name": "request", "span_id": "r",
              "trace_id": "t", "status": 200, "t_start_s": 0.0,
              "duration_s": 0.5, "pre_s": {}},
             {"component": "ingress", "name": "relay_attempt",
              "span_id": "h", "parent_id": "r", "outcome": "ok",
              "backend": 9000, "kind": "relay",
              "t_start_s": 0.0, "duration_s": 0.5}]
    out = wf.build_fleet_waterfall({"trace_id": "t", "spans": spans})
    _sum_ok(out)
    assert out["totals"].get("relay_backend") == 0.5
    assert out["proxy_overhead_s"] == 0.5  # nothing engine-attributed


# ------------------------------------------------------------ budgets units


def test_budget_sample_clips_segments_to_ttft_window():
    span = _span(CLEAN, ttft_s=0.06)
    s = wf.span_budget_sample(span)
    assert s["cls"] == "interactive" and s["ttft_s"] == 0.06
    # decode happens after first_token: not part of the TTFT budget
    assert "decode" not in s["segments"]
    assert abs(s["segments"]["engine_queue"] - 0.01) < 1e-9
    assert abs(s["segments"]["prefill"] - 0.05) < 1e-9
    # pre-submit pulls ARE client-visible TTFT: added on top
    s2 = wf.span_budget_sample(_span(CLEAN, ttft_s=0.06,
                                     hints={"pre_fabric_pull": 0.04}))
    assert abs(s2["ttft_s"] - 0.1) < 1e-9
    assert abs(s2["segments"]["fabric_pull"] - 0.04) < 1e-9


def test_class_budgets_and_dominant_segment():
    samples = [{"cls": "interactive", "ttft_s": 0.1, "wall_s": 0.3,
                "segments": {"engine_queue": 0.07, "prefill": 0.03}}
               for _ in range(10)]
    budgets = wf.class_budgets({"interactive": [dict(s) for s in samples]})
    b = budgets["interactive"]
    assert b["n"] == 10 and abs(b["ttft_p95_s"] - 0.1) < 1e-9
    assert abs(b["segments"]["engine_queue"]["frac_of_p95_ttft"] - 0.7) < 1e-3
    dom = wf.dominant_segment([dict(s) for s in samples])
    assert dom["segment"] == "engine_queue" and dom["n"] == 10


def test_merge_budget_samples_bounded():
    payloads = [{"samples": {"batch": [{"ttft_s": 0.1, "wall_s": 0.1,
                                        "segments": {}}] * 2000}}]
    merged = wf.merge_budget_samples(payloads)
    assert len(merged["batch"]) == wf.BUDGET_SAMPLE_CAP * 4


def test_quantile_interpolates():
    assert wf.quantile([], 0.5) is None
    assert wf.quantile([3.0], 0.95) == 3.0
    assert abs(wf.quantile([1.0, 2.0, 3.0, 4.0], 0.5) - 2.5) < 1e-12


# ------------------------------------------------- engine integration (CPU)


PROMPT_IDS = [(i * 13 + 7) % 255 + 1 for i in range(6)]


def test_engine_waterfall_real_request_and_budget(params):
    eng = Engine(params, CFG, _ec())
    eng.start()
    try:
        r = eng.generate(PROMPT_IDS, 6)
        out = eng.waterfall(r["rid"])
        assert out is not None
        _sum_ok(out)
        assert out["outcome"] == "done"
        assert out["unaccounted_s"] == 0.0
        names = {s["name"] for s in out["segments"]}
        assert "prefill" in names and "decode" in names
        assert names <= set(wf.SEGMENTS)
        assert "critical_path" in out
        # unknown rid: None, never a throw
        assert eng.waterfall(10 ** 9) is None
        budget = eng.latency_budget()
        assert budget["samples"], budget
        cls, samples = next(iter(budget["samples"].items()))
        assert samples[0]["ttft_s"] > 0
        assert budget["classes"][cls]["ttft_p95_s"] > 0
    finally:
        eng.stop(drain=False)


def test_waterfall_plane_off_costs_nothing(params):
    eng = Engine(params, CFG, _ec(telemetry=False))
    eng.start()
    try:
        # pre_hints on a telemetry-off engine: accepted, dropped, free
        r = eng.generate(PROMPT_IDS, 4, pre_hints={"fabric_pull": 0.01})
        assert eng.waterfall(r["rid"]) is None
        assert eng.latency_budget() == {"classes": {}, "samples": {}}
    finally:
        eng.stop(drain=False)


# --------------------------------------------- e2e through the real proxy


def _post_hdrs(port, path, body, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers.items())


def _get_json(port, path, timeout=30):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_fleet_waterfall_and_latency_through_real_proxy(params):
    api = APIServer()
    proxy = ServiceProxy(api)
    svc_port = find_free_ports(1)[0]
    api.create({
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": "fleet", "labels": {LABEL_ISVC: "fleet"},
                     "annotations": {PROXY_PORT_ANNOTATION: str(svc_port),
                                     RELAY_TIMEOUT_ANNOTATION: "5.0"}},
        "spec": {"selector": {"app": "fleet"}}})
    eng = Engine(params, CFG, _ec())
    srv = ModelServer([JetStreamModel("fleet", "", engine=eng)], port=0)
    srv.start()
    api.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "fleet-0", "labels": {"app": "fleet"},
                     "annotations": {POD_PORT_ANNOTATION: str(srv.port)}},
        "spec": {},
        "status": {"phase": "Running",
                   "conditions": [{"type": "Ready", "status": "True"}]}})
    proxy.sync()
    try:
        # warm the compile caches off the measured request — and pin the
        # header the unary relay reads the engine wall from
        _, _, whdrs = _post_hdrs(
            srv.port, "/v2/models/fleet/generate",
            {"text_input": "warm up", "parameters": {"max_tokens": 4}})
        assert "X-Engine-Wall-S" in whdrs
        body = {"text_input": "the quick brown fox",
                "parameters": {"max_tokens": 8}}
        code, out, hdrs = _post_hdrs(svc_port,
                                     "/v2/models/fleet/generate", body)
        assert code == 200
        tid = hdrs.get("X-Trace-Id")
        assert tid

        # --- assembled trace: deduped, causally ordered
        code, tr = _get_json(svc_port, f"/fleet/trace/{tid}")
        assert code == 200
        keys = [(s.get("trace_id"), s.get("span_id")) for s in tr["spans"]]
        assert len(keys) == len(set(keys))  # no double-scraped spans
        assert any(s.get("component") == "engine"
                   and "t_start_adj_s" in s for s in tr["spans"])

        # --- end-to-end waterfall on the ingress clock
        code, wfo = _get_json(svc_port, f"/fleet/trace/{tid}/waterfall")
        assert code == 200, wfo
        _sum_ok(wfo)
        names = {s["name"] for s in wfo["segments"]}
        assert {"ingress_parse", "admission"} <= names
        assert any(s.get("skew_adjusted") for s in wfo["segments"])
        assert names <= set(wf.SEGMENTS)
        assert wfo["clock_offsets"]
        assert wfo["proxy_overhead_s"] >= 0
        # attribution coverage on a clean request: nearly nothing escapes
        assert wfo["unaccounted_s"] <= 0.05 * wfo["wall_s"] + 0.005, wfo

        # unknown trace: 404, not an empty 200
        code, _ = _get_json(svc_port, "/fleet/trace/" + "0" * 32
                            + "/waterfall")
        assert code == 404

        # --- replica-local waterfall by rid (via the trace's engine span)
        eng_span = next(s for s in tr["spans"]
                        if s.get("component") == "engine")
        code, ew = _get_json(srv.port,
                             f"/engine/waterfall/{eng_span['rid']}")
        assert code == 200
        _sum_ok(ew)
        code, _ = _get_json(srv.port, "/engine/waterfall/999999999")
        assert code == 404

        # --- per-class fleet budget through the proxy
        for _ in range(3):
            _post_hdrs(svc_port, "/v2/models/fleet/generate", body)
        code, lat = _get_json(svc_port, "/fleet/latency")
        assert code == 200
        assert lat["classes"], lat
        cls = next(iter(lat["classes"].values()))
        assert cls["ttft_p95_s"] >= cls["ttft_p50_s"] > 0
        assert cls["segments"]  # the budget breakdown, not just a number
        assert lat["replicas_queried"] == ["fleet-0"]
    finally:
        proxy.shutdown()
        srv.stop()
        eng.stop(drain=False)
