"""E2E training-operator tests: real multi-process rendezvous through the
full reconcile path (SURVEY.md §4: go beyond upstream CI — actually run
distributed workloads as local processes)."""

import json
import shutil
import sys

import pytest

from kubeflow_tpu.core.cluster import Cluster
from kubeflow_tpu.training import api as tapi
from kubeflow_tpu.training.api import ReplicaSpec, TPUSpec, job
from kubeflow_tpu.training.client import TrainingClient
from kubeflow_tpu.training.frameworks import install


@pytest.fixture()
def tcluster():
    c = Cluster(cpu_nodes=1, tpu_slices=(("s0", "v5e", "2x4"),))
    install(c.api, c.manager)
    yield c
    c.shutdown()


def _client(c):
    return TrainingClient(c)


@pytest.mark.slow  # fast lane must stay under its 5-min budget (r1 #10)
def test_tpujob_distributed_psum_and_train(tcluster):
    """TPUJob with 2 workers → real jax.distributed rendezvous + psum."""
    spec = job(
        "TPUJob",
        "distcheck",
        {"Worker": ReplicaSpec(
            replicas=2,
            command=[sys.executable, "-u", "-m", "kubeflow_tpu.examples.distributed_check"],
            env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": "/root/repo"},
        )},
    )
    client = _client(tcluster)
    client.create_job(spec)
    assert client.wait_for_job("TPUJob", "distcheck", timeout=180) == tapi.SUCCEEDED
    logs = client.get_job_logs("TPUJob", "distcheck")
    assert len(logs) == 2
    joined = "\n".join(logs.values())
    assert "PSUM got=3.0 expected=3.0" in joined
    assert "TRAIN-OK" in joined


def test_tpujob_env_injection_and_gang(tcluster):
    """spec.tpu drives replica expansion, placement + rendezvous env."""
    spec = job(
        "TPUJob",
        "envjob",
        {"Worker": ReplicaSpec(
            command=[sys.executable, "-u", "-c",
                     "import os, json; print(json.dumps({k: v for k, v in os.environ.items() if k.startswith(('JAX_', 'TPU_', 'MEGASCALE_'))}))"],
        )},
        tpu=TPUSpec(accelerator="v5e", topology="2x4"),  # 8 chips → 2 hosts
    )
    client = _client(tcluster)
    client.create_job(spec)
    assert client.wait_for_job("TPUJob", "envjob", timeout=60) == tapi.SUCCEEDED

    pods = [tcluster.api.get("Pod", f"envjob-worker-{i}") for i in range(2)]
    # gang: both pods on the TPU slice, distinct hosts
    assert {p["spec"]["nodeName"] for p in pods} == {"s0-host-0", "s0-host-1"}
    # PodGroup created and bound
    assert tcluster.api.get("PodGroup", "envjob")["status"]["phase"] == "Running"

    for i, p in enumerate(pods):
        # runtime env (NOTE: this sandbox's TPU tunnel sitecustomize rewrites
        # TPU_TOPOLOGY at interpreter start, so TPU_* fidelity is asserted on
        # the pod spec — the real kubelet surface — below)
        envs = json.loads(tcluster.logs(f"envjob-worker-{i}").strip().splitlines()[-1])
        assert envs["JAX_NUM_PROCESSES"] == "2"
        assert envs["JAX_PROCESS_ID"] == str(i)
        assert envs["JAX_COORDINATOR_ADDRESS"].startswith("127.0.0.1:")
        assert "MEGASCALE_NUM_SLICES" not in envs  # single slice
        spec_env = {e["name"]: e["value"] for e in p["spec"]["containers"][0]["env"]}
        assert spec_env["TPU_TOPOLOGY"] == "2x4"
        assert spec_env["TPU_ACCELERATOR_TYPE"] == "tpu-v5-lite-podslice"
        assert spec_env["TPU_CHIPS_PER_HOST"] == "4"


def test_tpujob_multislice_megascale_env(tcluster):
    spec = job(
        "TPUJob",
        "ms",
        {"Worker": ReplicaSpec(
            command=[sys.executable, "-u", "-c",
                     "import os; print(os.environ.get('MEGASCALE_SLICE_ID'), os.environ.get('MEGASCALE_NUM_SLICES'), os.environ.get('JAX_NUM_PROCESSES'))"],
        )},
        tpu=TPUSpec(accelerator="v5e", topology="2x2", num_slices=2),  # 1 host/slice × 2
    )
    # no second slice exists → pods can't all gang-place on one slice; but
    # multislice jobs place per-slice. For the sim we only check env, so run
    # on CPU nodes by dropping the nodeSelector: use a cluster w/ two slices.
    c = Cluster(cpu_nodes=0, tpu_slices=(("a", "v5e", "2x2"), ("b", "v5e", "2x2")))
    install(c.api, c.manager)
    try:
        client = TrainingClient(c)
        client.create_job(spec)
        assert client.wait_for_job("TPUJob", "ms", timeout=60) == tapi.SUCCEEDED
        out = {i: c.logs(f"ms-worker-{i}").split() for i in range(2)}
        assert out[0][:3] == ["0", "2", "2"]
        assert out[1][:3] == ["1", "2", "2"]
    finally:
        c.shutdown()


def test_tfjob_tf_config(tcluster):
    spec = job(
        "TFJob",
        "tfj",
        {
            "PS": ReplicaSpec(command=[sys.executable, "-u", "-c", "import os; print(os.environ['TF_CONFIG'])"]),
            "Worker": ReplicaSpec(replicas=2, command=[sys.executable, "-u", "-c", "import os; print(os.environ['TF_CONFIG'])"]),
        },
    )
    client = _client(tcluster)
    client.create_job(spec)
    # no Chief → success = all workers done; PS runs a finite cmd here too
    assert client.wait_for_job("TFJob", "tfj", timeout=60) == tapi.SUCCEEDED
    cfg = json.loads(tcluster.logs("tfj-worker-1").strip())
    assert cfg["task"] == {"type": "worker", "index": 1}
    assert len(cfg["cluster"]["worker"]) == 2
    assert len(cfg["cluster"]["ps"]) == 1
    # distinct ports across the cluster spec
    all_addrs = [a for addrs in cfg["cluster"].values() for a in addrs]
    assert len(set(all_addrs)) == 3


@pytest.mark.slow
def test_pytorchjob_real_gloo_allreduce(tcluster):
    code = (
        "import os, datetime, torch, torch.distributed as dist\n"
        "dist.init_process_group('gloo', timeout=datetime.timedelta(seconds=60))\n"
        "t = torch.tensor([float(dist.get_rank() + 1)])\n"
        "dist.all_reduce(t)\n"
        "print('ALLREDUCE', t.item(), 'world', dist.get_world_size())\n"
    )
    spec = job(
        "PyTorchJob",
        "ptj",
        {
            "Master": ReplicaSpec(command=[sys.executable, "-u", "-c", code]),
            "Worker": ReplicaSpec(command=[sys.executable, "-u", "-c", code]),
        },
    )
    client = _client(tcluster)
    client.create_job(spec)
    assert client.wait_for_job("PyTorchJob", "ptj", timeout=120) == tapi.SUCCEEDED
    assert "ALLREDUCE 3.0 world 2" in tcluster.logs("ptj-master-0")


@pytest.mark.slow
def test_exitcode_restart_policy(tcluster, tmp_path):
    """exit 137 (SIGKILL/preemption) is retryable; pod is recreated."""
    marker = str(tmp_path / "marker")
    code = (
        "import os, sys\n"
        f"m = {marker!r}\n"
        "if not os.path.exists(m):\n"
        "    open(m, 'w').close(); sys.exit(137)\n"
        "print('RECOVERED')\n"
    )
    spec = job(
        "TPUJob",
        "pre",
        {"Worker": ReplicaSpec(command=[sys.executable, "-u", "-c", code], restart_policy="ExitCode")},
    )
    client = _client(tcluster)
    client.create_job(spec)
    assert client.wait_for_job("TPUJob", "pre", timeout=60) == tapi.SUCCEEDED
    j = client.get_job("TPUJob", "pre")
    assert j["status"]["restartCount"] == 1
    assert "RECOVERED" in tcluster.logs("pre-worker-0")


def test_exitcode_permanent_failure(tcluster):
    spec = job(
        "TPUJob",
        "perm",
        {"Worker": ReplicaSpec(command=[sys.executable, "-c", "import sys; sys.exit(2)"],
                               restart_policy="ExitCode")},
    )
    client = _client(tcluster)
    client.create_job(spec)
    assert client.wait_for_job("TPUJob", "perm", timeout=60) == tapi.FAILED
    j = client.get_job("TPUJob", "perm")
    from kubeflow_tpu.core.conditions import get_condition
    assert "exit code 2" in get_condition(j["status"], tapi.FAILED)["message"]


@pytest.mark.slow
def test_backoff_limit(tcluster):
    spec = job(
        "TPUJob",
        "loop",
        {"Worker": ReplicaSpec(command=[sys.executable, "-c", "import sys; sys.exit(137)"],
                               restart_policy="ExitCode")},
        run_policy={"backoffLimit": 1},
    )
    client = _client(tcluster)
    client.create_job(spec)
    assert client.wait_for_job("TPUJob", "loop", timeout=60) == tapi.FAILED
    assert client.get_job("TPUJob", "loop")["status"]["restartCount"] == 1


@pytest.mark.slow
def test_clean_pod_policy_and_ttl(tcluster):
    spec = job(
        "TPUJob",
        "clean",
        {"Worker": ReplicaSpec(command=[sys.executable, "-c", "print('ok')"])},
        run_policy={"cleanPodPolicy": "All", "ttlSecondsAfterFinished": 1},
    )
    client = _client(tcluster)
    client.create_job(spec)
    assert client.wait_for_job("TPUJob", "clean", timeout=60) == tapi.SUCCEEDED
    # pods cleaned
    assert tcluster.wait_for(
        lambda: not tcluster.api.list("Pod", label_selector={tapi.LABEL_JOB_NAME: "clean"}),
        timeout=30,
    )
    # TTL deletes the job itself
    assert tcluster.wait_for(lambda: client.get_job("TPUJob", "clean") is None, timeout=30)


def test_job_validation_rejects_bad_spec(tcluster):
    from kubeflow_tpu.core.api import Invalid
    bad = job("TFJob", "bad", {"Worker": ReplicaSpec(command=["true"])})
    bad["spec"]["replicaSpecs"]["Bogus"] = bad["spec"]["replicaSpecs"].pop("Worker")
    with pytest.raises(Invalid):
        tcluster.api.create(bad)


def test_pytorchjob_elastic_shrinks_on_worker_failure(tcluster, tmp_path):
    """ElasticPolicy: a permanently-failed Worker shrinks the world instead
    of failing the job; PET_* rendezvous bounds are injected."""
    worker_code = (
        "import os, time, sys\n"
        "marker = os.path.join(os.environ['MARKER_DIR'], 'died')\n"
        "assert os.environ['PET_MIN_REPLICAS'] == '1'\n"
        "if os.environ['RANK'] == '2' and not os.path.exists(marker):\n"
        "    open(marker, 'w').write('x'); sys.exit(1)\n"  # permanent (rc 1)
        "time.sleep(3)\n"
    )
    spec = job(
        "PyTorchJob",
        "elastic",
        {
            "Master": ReplicaSpec(
                replicas=1,
                command=[sys.executable, "-u", "-c", "import time; time.sleep(1.5); print('MASTER-DONE')"],
                env={"PYTHONPATH": "/root/repo"},
            ),
            "Worker": ReplicaSpec(
                replicas=2,
                command=[sys.executable, "-u", "-c", worker_code],
                env={"PYTHONPATH": "/root/repo", "MARKER_DIR": str(tmp_path)},
            ),
        },
    )
    spec["spec"]["elasticPolicy"] = {"minReplicas": 1, "maxReplicas": 4}
    client = _client(tcluster)
    client.create_job(spec)
    assert client.wait_for_job("PyTorchJob", "elastic", timeout=120) == tapi.SUCCEEDED
    final = client.get_job("PyTorchJob", "elastic")
    assert final["status"]["elasticReplicas"]["Worker"] == 1
    events = [e.get("reason") for e in tcluster.api.list("Event")]
    assert "JobScaledDown" in events


def test_pytorchjob_scale_job_clamps(tcluster):
    spec = job(
        "PyTorchJob",
        "scaleme",
        {"Worker": ReplicaSpec(
            replicas=2,
            command=[sys.executable, "-u", "-c", "import time; time.sleep(8)"],
        )},
    )
    spec["spec"]["elasticPolicy"] = {"minReplicas": 1, "maxReplicas": 3}
    client = _client(tcluster)
    client.create_job(spec)
    tcluster.wait_for(
        lambda: len([p for p in tcluster.api.list("Pod") if p["metadata"]["name"].startswith("scaleme")]) == 2,
        timeout=30,
    )
    client.scale_job("PyTorchJob", "scaleme", 10)  # clamped to max 3
    assert tcluster.wait_for(
        lambda: len([p for p in tcluster.api.list("Pod") if p["metadata"]["name"].startswith("scaleme")]) == 3,
        timeout=30,
    )
    client.delete_job("PyTorchJob", "scaleme")


@pytest.mark.slow  # pod spin-up + 5s worker: keep the fast lane in budget
@pytest.mark.skipif(shutil.which("mpirun") is None,
                    reason="no system mpirun AND the vendored tools/mpirun.cc "
                           "build failed (modeled path covered by "
                           "test_mpijob_launcher_hostfile_configmap)")
def test_mpijob_launcher_runs_real_mpirun(tcluster):
    """VERDICT r2 #8: when a real MPI runtime exists, the Launcher pod must
    be able to exec `mpirun` and spawn ranks (local slots — the pod 'hosts'
    in the hostfile are not ssh-able on this box)."""
    launcher_code = (
        "import os, subprocess, sys\n"
        "out = subprocess.run(['mpirun', '--allow-run-as-root', '--oversubscribe',\n"
        "                      '-np', '2', '--host', 'localhost:2', sys.executable, '-c',\n"
        "                      'import os; print(\"MPIRANK\", os.environ.get(\"OMPI_COMM_WORLD_RANK\", \"?\"))'],\n"
        "                     capture_output=True, text=True, timeout=60)\n"
        "sys.stdout.write(out.stdout + out.stderr)\n"
        "sys.exit(out.returncode)\n"
    )
    spec = job(
        "MPIJob",
        "mpireal",
        {
            "Launcher": ReplicaSpec(replicas=1, command=[sys.executable, "-u", "-c", launcher_code]),
            "Worker": ReplicaSpec(replicas=1, command=[sys.executable, "-u", "-c", "import time; time.sleep(5)"]),
        },
    )
    spec["spec"].setdefault("runPolicy", {})["cleanPodPolicy"] = "Running"
    client = _client(tcluster)
    client.create_job(spec)
    assert client.wait_for_job("MPIJob", "mpireal", timeout=90) == tapi.SUCCEEDED
    log = tcluster.logs("mpireal-launcher-0")
    assert log.count("MPIRANK") == 2, log


def test_mpijob_launcher_hostfile_configmap(tcluster):
    """MPIJob launcher semantics (SURVEY.md §2a MPIJob row): a hostfile
    ConfigMap rendered by the controller, mounted into the Launcher pod and
    readable at the path OMPI_MCA_orte_default_hostfile points to."""
    launcher_code = (
        "import os\n"
        "path = os.environ['MPI_HOSTFILE']\n"
        "assert path == os.environ['OMPI_MCA_orte_default_hostfile']\n"
        "print('HOSTFILE:', open(path).read().replace('\\n', '|'))\n"
    )
    spec = job(
        "MPIJob",
        "mpi",
        {
            "Launcher": ReplicaSpec(replicas=1, command=[sys.executable, "-u", "-c", launcher_code]),
            "Worker": ReplicaSpec(replicas=2, command=[sys.executable, "-u", "-c", "import time; time.sleep(5)"]),
        },
    )
    spec["spec"].setdefault("runPolicy", {})["cleanPodPolicy"] = "Running"
    client = _client(tcluster)
    client.create_job(spec)
    assert client.wait_for_job("MPIJob", "mpi", timeout=60) == tapi.SUCCEEDED
    cm = tcluster.api.get("ConfigMap", "mpi-hostfile")
    assert cm["data"]["hostfile"] == "mpi-worker-0 slots=1\nmpi-worker-1 slots=1"
    log = tcluster.logs("mpi-launcher-0")
    assert "HOSTFILE: mpi-worker-0 slots=1|mpi-worker-1 slots=1" in log


def _pod_env(tcluster, name) -> dict:
    """Injected env from the CREATED Pod object (no need to run it — the
    rendezvous-env rendering is what these framework tests cover; the
    pod-actually-runs path is exercised by the TFJob/TPUJob/PyTorch E2Es,
    and skipping 4 interpreter startups per niche framework keeps the fast
    lane inside its budget)."""
    pod = tcluster.api.get("Pod", name)
    return {e["name"]: e["value"] for e in pod["spec"]["containers"][0].get("env", [])
            if "value" in e}


def test_mxjob_dmlc_env(tcluster):
    """MXJob: DMLC scheduler/server/worker rendezvous env on rendered pods."""
    show = [sys.executable, "-u", "-c", "pass"]
    spec = job(
        "MXJob",
        "mx",
        {
            "Scheduler": ReplicaSpec(replicas=1, command=show),
            "Server": ReplicaSpec(replicas=1, command=show),
            "Worker": ReplicaSpec(replicas=2, command=show),
        },
    )
    client = _client(tcluster)
    client.create_job(spec)
    assert tcluster.wait_for(
        lambda: tcluster.api.try_get("Pod", "mx-worker-1") is not None
        and tcluster.api.try_get("Pod", "mx-scheduler-0") is not None,
        timeout=30)
    w1 = _pod_env(tcluster, "mx-worker-1")
    assert w1["DMLC_ROLE"] == "worker" and w1["DMLC_WORKER_ID"] == "1"
    assert w1["DMLC_NUM_WORKER"] == "2" and w1["DMLC_NUM_SERVER"] == "1"
    s = _pod_env(tcluster, "mx-scheduler-0")
    assert s["DMLC_ROLE"] == "scheduler"
    assert s["DMLC_PS_ROOT_PORT"] == w1["DMLC_PS_ROOT_PORT"]


def test_paddlejob_trainer_endpoints(tcluster):
    """PaddleJob: collective-mode trainer endpoint rendezvous env on
    rendered pods (see _pod_env for why spec-level)."""
    show = [sys.executable, "-u", "-c", "pass"]
    spec = job("PaddleJob", "pd", {"Worker": ReplicaSpec(replicas=2, command=show)})
    client = _client(tcluster)
    client.create_job(spec)
    assert tcluster.wait_for(
        lambda: tcluster.api.try_get("Pod", "pd-worker-0") is not None
        and tcluster.api.try_get("Pod", "pd-worker-1") is not None,
        timeout=30)
    w0 = _pod_env(tcluster, "pd-worker-0")
    w1 = _pod_env(tcluster, "pd-worker-1")
    eps = w0["PADDLE_TRAINER_ENDPOINTS"].split(",")
    assert len(eps) == 2 and w0["PADDLE_TRAINER_ENDPOINTS"] == w1["PADDLE_TRAINER_ENDPOINTS"]
    assert w0["PADDLE_CURRENT_ENDPOINT"] == eps[0] and w1["PADDLE_CURRENT_ENDPOINT"] == eps[1]
    assert w0["PADDLE_TRAINER_ID"] == "0" and w1["PADDLE_TRAINER_ID"] == "1"
    assert w0["TRAINING_ROLE"] == "TRAINER" and w0["PADDLE_TRAINERS_NUM"] == "2"


# slow lane: ~14s E2E; the shrink path keeps fast coverage via test_pytorchjob_elastic_shrinks
@pytest.mark.slow
def test_pytorchjob_elastic_scale_up_after_shrink(tcluster, tmp_path):
    """Elastic scale-UP: after a shrink, growth re-expands toward the spec
    count once the cooldown passes (opt-in via elasticPolicy.scaleUp)."""
    worker_code = (
        "import os, time, sys\n"
        "marker = os.path.join(os.environ['MARKER_DIR'], 'died-' + os.environ['RANK'])\n"
        "if os.environ['RANK'] == '2' and not os.path.exists(marker):\n"
        "    open(marker, 'w').write('x'); sys.exit(1)\n"
        "time.sleep(6)\n"
    )
    spec = job(
        "PyTorchJob",
        "growback",
        {
            "Master": ReplicaSpec(
                replicas=1,
                command=[sys.executable, "-u", "-c", "import time; time.sleep(5); print('MASTER-DONE')"],
            ),
            "Worker": ReplicaSpec(
                replicas=2,
                command=[sys.executable, "-u", "-c", worker_code],
                env={"MARKER_DIR": str(tmp_path)},
            ),
        },
    )
    spec["spec"]["elasticPolicy"] = {
        "minReplicas": 1, "maxReplicas": 4, "scaleUp": True,
        "scaleUpCooldownSeconds": 0.5,
    }
    client = _client(tcluster)
    client.create_job(spec)
    # shrink happens when worker-1 dies, growth restores it after cooldown
    assert client.wait_for_job("PyTorchJob", "growback", timeout=120) == tapi.SUCCEEDED
    final = client.get_job("PyTorchJob", "growback")
    assert "elasticReplicas" not in final["status"], final["status"].get("elasticReplicas")
    events = [e.get("reason") for e in tcluster.api.list("Event")]
    assert "JobScaledDown" in events and "JobScaledUp" in events


@pytest.mark.slow
def test_tpujob_auto_resume_from_checkpoint(tcluster, tmp_path):
    """Auto-resume (SURVEY.md §5): a TPUJob worker preempted mid-run (exit
    137, retryable) restarts and continues from the newest checkpoint — step
    continuity, not a step-0 restart."""
    spec = job(
        "TPUJob",
        "resume",
        {"Worker": ReplicaSpec(
            replicas=1,
            restart_policy="ExitCode",
            command=[sys.executable, "-u", "-m", "kubeflow_tpu.examples.bert_worker"],
            env={
                "JAX_PLATFORMS": "cpu", "PYTHONPATH": "/root/repo",
                "TRAIN_STEPS": "12", "FAIL_AT_STEP": "7",
                "FAIL_MARKER": str(tmp_path / "died"),
            },
        )},
    )
    spec["spec"]["checkpoint"] = {"dir": str(tmp_path / "ckpt"), "everySteps": 3}
    client = _client(tcluster)
    client.create_job(spec)
    assert client.wait_for_job("TPUJob", "resume", timeout=240) == tapi.SUCCEEDED
    j = client.get_job("TPUJob", "resume")
    assert j["status"]["restartCount"] == 1
    log = tcluster.logs("resume-worker-0")
    # first run: fresh start, died at 7 with checkpoints saved at 3 and 6
    assert "resumed_from=0" in log
    # second run: resumed from the last DURABLE checkpoint (the step-6 save
    # is async; a preemption may kill the process before it commits)
    import re
    resumes = [int(m) for m in re.findall(r"resumed_from=(\d+)", log)]
    assert resumes[0] == 0 and resumes[1] in (3, 6), resumes
    assert "TRAIN-DONE step=12" in log
    # continuity: the resumed run starts at K+1, never back at step 1
    resumed_part = log.split(f"resumed_from={resumes[1]}", 1)[1]
    assert f"step={resumes[1] + 1} " in resumed_part
    assert "step=1 " not in resumed_part


@pytest.mark.slow
def test_tpujob_gang_restart_on_single_worker_failure(tcluster, tmp_path):
    """Slice-level failure domain (SURVEY.md §5): one worker of a 2-worker
    jax.distributed gang preempted mid-run restarts the WHOLE gang (the
    survivor is wedged in collectives), both workers re-rendezvous, resume
    from the newest checkpoint, and the job completes — one backoff count."""
    spec = job(
        "TPUJob",
        "gangres",
        {"Worker": ReplicaSpec(
            replicas=2,
            restart_policy="ExitCode",
            command=[sys.executable, "-u", "-m", "kubeflow_tpu.examples.bert_worker"],
            env={
                "JAX_PLATFORMS": "cpu", "PYTHONPATH": "/root/repo",
                "TRAIN_STEPS": "10", "FAIL_AT_STEP": "5", "FAIL_RANK": "1",
                "FAIL_MARKER": str(tmp_path / "died"),
            },
        )},
    )
    spec["spec"]["checkpoint"] = {"dir": str(tmp_path / "ckpt"), "everySteps": 2}
    client = _client(tcluster)
    client.create_job(spec)
    assert client.wait_for_job("TPUJob", "gangres", timeout=300) == tapi.SUCCEEDED
    j = client.get_job("TPUJob", "gangres")
    assert j["status"]["restartCount"] == 1  # one gang restart, not per-pod
    events = [e.get("reason") for e in tcluster.api.list("Event")]
    assert "SliceRestarting" in events
    # BOTH workers ran twice: fresh (resumed_from=0) then resumed from a
    # durable checkpoint — the healthy worker restarted too
    import re
    for w in (0, 1):
        log = tcluster.logs(f"gangres-worker-{w}")
        resumes = [int(m) for m in re.findall(r"resumed_from=(\d+)", log)]
        assert len(resumes) == 2 and resumes[0] == 0 and resumes[1] > 0, (w, resumes)
        assert "TRAIN-DONE step=10" in log


def test_dns_host_mode_renders_headless_service_names(tcluster):
    """spec.network.hostMode=dns: rendezvous env carries the headless-Service
    DNS names that the common controller's per-replica Services resolve to —
    the real-deployment rendering of the simulator's 127.0.0.1."""
    from kubeflow_tpu.training.frameworks import TFJobController, TPUJobController

    spec = job(
        "TFJob", "dnsj",
        {"PS": ReplicaSpec(command=["x"]), "Worker": ReplicaSpec(replicas=2, command=["x"])},
    )
    spec["spec"]["network"] = {"hostMode": "dns"}
    spec["metadata"]["annotations"] = {
        "training.kubeflow.org/rendezvous-ports": "[5001, 5002, 5003]"}
    replicas = spec["spec"]["replicaSpecs"]
    for r in replicas.values():
        r.setdefault("replicas", 1)
    ctrl = TFJobController(tcluster.api)
    env = ctrl.set_cluster_spec(spec, "Worker", 1, replicas)
    cfg = json.loads(env["TF_CONFIG"])
    assert cfg["cluster"]["worker"] == [
        "dnsj-worker-0.default.svc.cluster.local:5002",
        "dnsj-worker-1.default.svc.cluster.local:5003",
    ]
    assert cfg["cluster"]["ps"] == ["dnsj-ps-0.default.svc.cluster.local:5001"]

    tspec = job("TPUJob", "dnst", {"Worker": ReplicaSpec(replicas=2, command=["x"])})
    tspec["spec"]["network"] = {"hostMode": "dns", "clusterDomain": "corp.local"}
    tspec["metadata"]["annotations"] = {
        "training.kubeflow.org/rendezvous-ports": "[6001, 6002]"}
    tenv = TPUJobController(tcluster.api).set_cluster_spec(
        tspec, "Worker", 0, tspec["spec"]["replicaSpecs"])
    assert tenv["JAX_COORDINATOR_ADDRESS"] == "dnst-worker-0.default.svc.corp.local:6001"
    assert tenv["TPU_WORKER_HOSTNAMES"] == (
        "dnst-worker-0.default.svc.corp.local,dnst-worker-1.default.svc.corp.local")

    # the names match what _ensure_service creates: run a real (local-mode)
    # job and check the per-replica Service objects exist with those names
    rspec = job("TPUJob", "svcj", {"Worker": ReplicaSpec(
        replicas=2, command=[sys.executable, "-c", "pass"])})
    client = _client(tcluster)
    client.create_job(rspec)
    assert client.wait_for_job("TPUJob", "svcj", timeout=60) == tapi.SUCCEEDED
    for i in range(2):
        assert tcluster.api.try_get("Service", f"svcj-worker-{i}") is not None
