class Router:
    def __init__(self):
        self.per_tenant_credit: dict = {}

    def note(self, tenant):
        self.per_tenant_credit[tenant] = \
            self.per_tenant_credit.get(tenant, 0) + 1
