import threading


class Poller:
    def start(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass
