from .registry import REGISTRY

TOKENS = REGISTRY.gauge("tenant_tokens", "per-tenant bucket level")


def on_admit(tenant, level):
    TOKENS.set(level, tenant=tenant)


def on_prune(tenant):
    TOKENS.remove(tenant=tenant)
