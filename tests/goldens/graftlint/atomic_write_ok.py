import json
import os


def save_state(path, state):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f)
    os.replace(tmp, path)
