import threading


class Table:
    def __init__(self):
        self._lock = threading.Lock()
        self._rows: dict = {}  # guarded-by: _lock

    def put(self, k, v):
        with self._lock:
            self._rows[k] = v

    def get(self, k):
        return self._rows.get(k)
