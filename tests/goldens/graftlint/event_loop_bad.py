import json
import time
import urllib.request


# graftlint: event-loop
def on_readable(state):
    data = state.sock.recv(65536)  # blocking recv: no BlockingIOError guard
    if not data:
        return None
    body = json.loads(data)  # body parsing on the loop thread
    if body.get("retry"):
        time.sleep(0.05)  # sleeps the whole loop
    return urllib.request.urlopen(body["url"])  # sync dial+read on the loop
