import jax
import numpy as np


class Engine:
    k_pool = None
    v_pool = None

    def export_pages(self, pages):  # graftlint: hot-path
        # shard-native: each block is one shard's own addressable bytes
        return [np.asarray(s.data[:, pages])
                for s in self.k_pool.addressable_shards]

    def debug_dump(self):  # cold path: no marker, gathers are fine
        return (np.asarray(self.k_pool), jax.device_get(self.v_pool))
