def pick(xs):
    import numpy as np
    return int(np.argmin(xs))
