import numpy as np


def pick(xs):
    return int(np.argmin(xs))
