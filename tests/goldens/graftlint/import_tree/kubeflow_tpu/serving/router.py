from . import helper
