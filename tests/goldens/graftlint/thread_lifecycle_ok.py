import threading


class Poller:
    def start(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def stop(self):
        self._t.join(timeout=5)

    def _run(self):
        pass


def fan_out(jobs):
    ts = [threading.Thread(target=j) for j in jobs]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
