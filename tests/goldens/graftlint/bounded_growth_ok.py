class Router:
    def __init__(self):
        self.per_tenant_credit: dict = {}

    def note(self, tenant):
        self.per_tenant_credit[tenant] = \
            self.per_tenant_credit.get(tenant, 0) + 1

    def prune(self, live):
        for t in [t for t in self.per_tenant_credit if t not in live]:
            self.per_tenant_credit.pop(t)
