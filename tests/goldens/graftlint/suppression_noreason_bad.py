import json


def save_state(path, state):
    # graftlint: disable=atomic-write
    with open(path, "w") as f:
        json.dump(state, f)
