class Limiter:
    def __init__(self):
        self.inflight = 0

    def handle(self, work):
        self.inflight += 1  # graftlint: acquires=slot
        try:
            work()
        finally:
            self.inflight -= 1  # graftlint: releases=slot
