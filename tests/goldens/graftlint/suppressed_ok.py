import json


def save_state(path, state):
    # graftlint: disable=atomic-write -- scratch file in a test tmpdir,
    # no reader races the writer
    with open(path, "w") as f:
        json.dump(state, f)
