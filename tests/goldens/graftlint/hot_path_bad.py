import json
import threading

LOCK = threading.Lock()
TABLE: dict = {}


def observe(raw):  # graftlint: hot-path
    body = json.loads(raw)
    with LOCK:
        for k, v in TABLE.items():
            body[k] = v
    return body
