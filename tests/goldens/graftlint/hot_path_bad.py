import json
import re
import threading
import time

LOCK = threading.Lock()
TABLE: dict = {}


def observe(raw):  # graftlint: hot-path
    body = json.loads(raw)
    body["at"] = time.time()
    pat = re.compile(body.get("filter", ".*"))
    with LOCK:
        for k, v in TABLE.items():
            if pat.match(k):
                body[k] = v
    return body
