import json
import threading
import time

LOCK = threading.Lock()
TABLE: dict = {}


def observe(raw):  # graftlint: hot-path
    body = json.loads(raw)
    body["at"] = time.time()
    with LOCK:
        for k, v in TABLE.items():
            body[k] = v
    return body
