import selectors

SEL = selectors.DefaultSelector()


# graftlint: event-loop
def on_readable(state, work_queue):
    try:
        data = state.sock.recv(65536)
    except (BlockingIOError, InterruptedError):
        return
    except OSError:
        SEL.unregister(state.sock)
        return
    if not data:
        SEL.unregister(state.sock)
        return
    state.buf += data
    # framing only: parsing and backend I/O happen on the worker pool
    idx = state.buf.find(b"\r\n\r\n")
    if idx >= 0:
        work_queue.put(bytes(state.buf[:idx]))
        del state.buf[:idx + 4]


def worker(work_queue):
    # unmarked: workers may block (they own one request, not the loop)
    head = work_queue.get()
    return head.split(b"\r\n")
