import re
import threading
import time

LOCK = threading.Lock()
TABLE: dict = {}
KEY_PAT = re.compile(r"[a-z_]+")  # compiled once, outside any hot path


def observe(body):  # graftlint: hot-path
    body["at"] = time.perf_counter()
    key = body.get("k")
    if key is None or not KEY_PAT.match(key):
        return None
    with LOCK:
        cached = TABLE.get(key)
    return cached
