import threading

LOCK = threading.Lock()
TABLE: dict = {}


def observe(body):  # graftlint: hot-path
    with LOCK:
        cached = TABLE.get(body.get("k"))
    return cached
