import threading
import time

LOCK = threading.Lock()
TABLE: dict = {}


def observe(body):  # graftlint: hot-path
    body["at"] = time.perf_counter()
    with LOCK:
        cached = TABLE.get(body.get("k"))
    return cached
