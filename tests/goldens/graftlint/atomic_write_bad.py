import json


def save_state(path, state):
    with open(path, "w") as f:
        json.dump(state, f)
