import jax
import numpy as np


class Engine:
    k_pool = None
    v_pool = None

    def export_pages(self, pages):  # graftlint: hot-path
        blob_k = np.asarray(self.k_pool[:, pages])
        blob_v = jax.device_get(self.v_pool)
        return blob_k, blob_v
