"""Performance-introspection tests (ISSUE 11): the analytical FLOPs model
against hand-computed counts, the goodput ledger's
``goodput + waste == dispatched`` invariant under preemption storms and
speculative chaos, phase-timeline ring bounds, the ``/engine/perf`` and
``POST /engine/profile`` endpoint contracts (with the profiler artifact
store's count/byte caps and stop-time cleanup), the proxy's fleet cache
view pruning on pod churn, and metric exposition.
"""

import json
import os
import time
import urllib.error
import urllib.request

import jax
import pytest

from kubeflow_tpu.core.api import APIServer
from kubeflow_tpu.serving.api import LABEL_ISVC
from kubeflow_tpu.serving.controllers import (POD_PORT_ANNOTATION,
                                              PROXY_PORT_ANNOTATION)
from kubeflow_tpu.serving.engine import Engine, EngineConfig
from kubeflow_tpu.serving.engine import model as M
from kubeflow_tpu.serving.engine.faults import FaultConfig
from kubeflow_tpu.serving.engine.perf import (FlopsModel, PerfLedger,
                                              ProfileStore, TickTimeline,
                                              TIMELINE_PHASES, WASTE_REASONS,
                                              platform_peak_flops)
from kubeflow_tpu.serving.engine.scheduler import SchedulerConfig
from kubeflow_tpu.serving.engine.serve import JetStreamModel
from kubeflow_tpu.serving.errors import RequestError
from kubeflow_tpu.serving.router import ServiceProxy
from kubeflow_tpu.serving.server import ModelServer
from kubeflow_tpu.utils.net import find_free_ports

pytestmark = pytest.mark.perf

# vocab >= 256: the JetStream byte tokenizer addresses ids 0..255
CFG = M.DecoderConfig(vocab_size=288, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=128)


@pytest.fixture(scope="module")
def params():
    return M.init(jax.random.PRNGKey(0), CFG)


def _ec(**kw):
    base = dict(max_slots=4, num_pages=128, page_size=8,
                max_pages_per_slot=16)
    base.update(kw)
    return EngineConfig(**base)


def _wait(pred, timeout=30.0, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    raise TimeoutError(f"timed out waiting for {msg}")


def _assert_invariant(snap):
    """goodput + attributed waste must EXACTLY equal dispatched FLOPs —
    the acceptance criterion, checked as the ledger's own identity."""
    dispatched = snap["dispatched_flops"]
    accounted = snap["goodput_flops"] + sum(snap["waste_flops"].values())
    assert accounted == pytest.approx(dispatched, rel=1e-12), (
        f"goodput+waste != dispatched: {accounted} vs {dispatched} "
        f"(waste: {snap['waste_flops']})")
    assert snap["accounted_flops"] == pytest.approx(dispatched, rel=1e-12)
    for reason in snap["waste_flops"]:
        assert reason in WASTE_REASONS, f"unknown waste reason {reason!r}"


# ------------------------------------------------- FLOPs model vs hand counts


def test_flops_model_prefill_hand_count():
    c = CFG
    fm = FlopsModel(c)
    hd = c.head_dim
    # hand count: per-token matmuls (wq wk wv wo w1 w3 w2 + unembed)
    per_layer = 2 * (c.d_model * c.n_heads * hd
                     + 2 * c.d_model * c.n_kv_heads * hd
                     + c.n_heads * hd * c.d_model
                     + 3 * c.d_model * c.d_ff)
    lin = c.n_layers * per_layer + 2 * c.d_model * c.vocab_size
    assert fm.per_token == lin
    # causal attention over L=5: per layer 4*n_heads*hd*sum(1..5)
    L = 5
    attn = c.n_layers * 4 * c.n_heads * hd * (L * (L + 1) // 2)
    assert fm.prefill_row(L) == L * lin + attn
    # chunk at history 3: positions 4..3+L each attend history+i
    attn_hist = c.n_layers * 4 * c.n_heads * hd * (
        sum(3 + i for i in range(1, L + 1)))
    assert fm.prefill_row(L, history=3) == L * lin + attn_hist
    assert fm.prefill_row(0) == 0.0


def test_flops_model_decode_and_verify_hand_count():
    c = CFG
    fm = FlopsModel(c)
    S = 37
    attn = c.n_layers * 4 * c.n_heads * c.head_dim * S
    assert fm.decode_row(S) == fm.per_token + attn
    # fused verify: k positions at ~context S
    assert fm.verify_row(S, 4) == 4 * fm.decode_row(S)


def test_flops_model_lora_delta():
    r, n_ad = 4, 3
    import numpy as np

    hd = CFG.head_dim
    table = {"wq": {"A": np.zeros((n_ad, CFG.n_layers, CFG.d_model, r)),
                    "B": np.zeros((n_ad, CFG.n_layers, r,
                                   CFG.n_heads * hd))}}
    fm = FlopsModel(CFG, lora=table)
    delta = CFG.n_layers * 2 * r * (CFG.d_model + CFG.n_heads * hd)
    assert fm.per_token == FlopsModel(CFG).per_token + delta


def test_platform_peak_table(monkeypatch):
    from kubeflow_tpu.scheduler.topology import VARIANTS

    label, peak = platform_peak_flops("cpu")
    assert label == "cpu" and peak > 0
    label, peak = platform_peak_flops("tpu", "TPU v5 lite core", 1)
    assert label == "tpu-v5e" and peak == VARIANTS["v5e"].flops_bf16
    label, peak = platform_peak_flops("tpu", "TPU v5 lite core", 4)
    assert peak == 4 * VARIANTS["v5e"].flops_bf16
    monkeypatch.setenv("ENGINE_PEAK_FLOPS", "123.0")
    label, peak = platform_peak_flops("cpu")
    assert peak == 123.0 and label.endswith("!")


# ------------------------------------------------------------- ledger units


def test_ledger_invariant_by_construction():
    led = PerfLedger(peak_flops=1e9, platform="cpu", window_s=60)
    led.charge("prefill", 100.0, 10, None)
    led.charge("decode", 50.0, 5, None)
    led.charge("verify", 30.0, 3, "spec_reject")
    led.charge("prefill", 20.0, 2, "preempt_recompute")
    snap = led.snapshot()
    assert snap["dispatched_flops"] == 200.0
    assert snap["goodput_flops"] == 150.0
    assert snap["waste_flops"] == {"spec_reject": 30.0,
                                   "preempt_recompute": 20.0}
    assert snap["accounted_flops"] == snap["dispatched_flops"]
    assert 0.0 < snap["goodput_ratio"] < 1.0
    assert snap["mfu"] > 0.0
    # zero-charge and idle behavior
    led2 = PerfLedger(1e9, "cpu")
    assert led2.goodput_ratio() == 1.0 and led2.mfu() == 0.0
    led2.charge("decode", 0.0, 1, None)  # no-op
    assert led2.snapshot()["dispatched_flops"] == 0.0


def test_timeline_ring_bounds_unit():
    tl = TickTimeline(capacity=4)
    for t in range(10):
        tl.note(t, "admit", 0.001)
        tl.note(t, "decode_dispatch", 0.002)
        tl.note(t, "decode_dispatch", 0.003)  # repeated segments sum
    assert len(tl) == 4
    snap = tl.snapshot()
    assert [r["tick"] for r in snap] == [6, 7, 8, 9]
    assert snap[-1]["segments"]["decode_dispatch"] == pytest.approx(0.005)
    assert snap[-1]["segments"]["admit"] == pytest.approx(0.001)


# --------------------------------------------------- engine-level invariants


def test_goodput_invariant_plain_run(params):
    eng = Engine(params, CFG, _ec())
    eng.start()
    try:
        prompts = [[(i * 13 + j) % 255 + 1 for j in range(10 + i)]
                   for i in range(6)]
        futs = [eng.generate_async(p, 8) for p in prompts]
        results = [f.result(timeout=120) for f in futs]
        assert all(r["num_tokens"] == 8 for r in results)
        snap = eng.perf_snapshot()
        _assert_invariant(snap)
        assert snap["flops_by_kind"]["prefill"] > 0
        assert snap["flops_by_kind"]["decode"] > 0
        # prefill goodput covers every prompt position exactly once
        assert snap["positions_by_kind"]["prefill"] == sum(
            len(p) for p in prompts)
    finally:
        eng.stop()


def test_goodput_invariant_preemption_storm(params):
    eng = Engine(params, CFG, _ec(
        max_slots=2,
        chaos=FaultConfig(seed=7, preempt_every=4),
        scheduler=SchedulerConfig(swap_policy="recompute")))
    eng.start()
    try:
        prompts = [[(i * 17 + j) % 255 + 1 for j in range(12)]
                   for i in range(6)]
        futs = [eng.generate_async(p, 10) for p in prompts]
        results = [f.result(timeout=180) for f in futs]
        assert all(r["num_tokens"] == 10 for r in results)
        assert sum(r["preemptions"] for r in results) > 0
        snap = eng.perf_snapshot()
        _assert_invariant(snap)
        # drop-preempt resumes re-prefill already-computed context: that
        # work must land under preempt_recompute, not goodput
        assert snap["waste_flops"].get("preempt_recompute", 0) > 0
        assert snap["goodput_ratio"] < 1.0
    finally:
        eng.stop()


def test_spec_reject_waste_matches_accept_rate(params):
    K = 4
    eng = Engine(params, CFG, _ec(
        speculative="prompt_lookup", spec_max_draft=K, spec_ngram=2))
    eng.start()
    try:
        # repetitive prompts so prompt-lookup drafts fire and some accept
        base = [5, 9, 5, 9, 5, 9, 5, 9, 5, 9, 5, 9]
        futs = [eng.generate_async(base + [i + 30], 16) for i in range(4)]
        for f in futs:
            f.result(timeout=180)
        stats = eng.stats
        snap = eng.perf_snapshot()
        _assert_invariant(snap)
        proposed, accepted = stats["spec_proposed"], stats["spec_accepted"]
        assert proposed > 0
        rejected = snap["waste_positions"].get("spec_reject", 0)
        # per verify pass: charged k=d+1, committed=acc+1 -> rejected
        # positions == proposed - accepted, up to one budget-cut pass per
        # request (the final pass may commit fewer than it accepted)
        assert abs(rejected - (proposed - accepted)) <= K * len(futs), (
            f"spec_reject {rejected} vs proposed-accepted "
            f"{proposed - accepted}")
    finally:
        eng.stop()


def test_handoff_degraded_attribution(params):
    eng = Engine(params, CFG, _ec())
    eng.start()
    try:
        prompt = [(j * 11) % 255 + 1 for j in range(20)]
        # resume_len mismatch -> the import degrades at submit and the
        # decode-side re-prefill is the prefill replica's work redone
        r = eng.generate(prompt, 4, kv_import=(object(), 128, 999))
        assert r["num_tokens"] == 4
        snap = eng.perf_snapshot()
        _assert_invariant(snap)
        assert snap["waste_positions"].get("handoff_degraded") == len(prompt)
        assert snap["waste_flops"]["handoff_degraded"] > 0
    finally:
        eng.stop()


def test_waste_hint_validated(params):
    eng = Engine(params, CFG, _ec())
    try:
        with pytest.raises(RequestError):
            eng.generate_async([1, 2, 3], 2, waste_hint="bogus_reason")
    finally:
        eng.stop(drain=False)


def test_failover_reprefill_hint_through_model(params):
    eng = Engine(params, CFG, _ec())
    eng.start()
    m = JetStreamModel("m", engine=eng)
    try:
        out = m.generate({"text_input": "hello failover",
                          "parameters": {"max_tokens": 8,
                                         "resume_token_ids": [65, 66, 67]}})
        assert out["tokens"] == 8
        snap = eng.perf_snapshot()
        _assert_invariant(snap)
        # prompt + resume ids re-prefill under failover_reprefill
        assert snap["waste_positions"].get("failover_reprefill", 0) \
            == len("hello failover") + 3
    finally:
        eng.stop()


def test_perf_plane_off_charges_nothing(params):
    eng = Engine(params, CFG, _ec(perf=False))
    eng.start()
    try:
        eng.generate([1, 2, 3, 4, 5, 6, 7, 8, 9], 4)
        snap = eng.perf_snapshot()
        assert snap["enabled"] is False
        assert snap["dispatched_flops"] == 0.0
        assert len(snap["timeline"]) == 0
    finally:
        eng.stop()


def test_timeline_ring_bounds_engine(params):
    eng = Engine(params, CFG, _ec(perf_timeline_capacity=8))
    eng.start()
    try:
        eng.generate(list(range(1, 12)), 24)
        assert 0 < len(eng.timeline) <= 8
        for rec in eng.timeline.snapshot():
            assert set(rec["segments"]) <= set(TIMELINE_PHASES)
        # a decode-heavy run must attribute decode time
        segs = {}
        for rec in eng.timeline.snapshot():
            for k, v in rec["segments"].items():
                segs[k] = segs.get(k, 0.0) + v
        assert segs.get("decode_dispatch", 0) > 0
    finally:
        eng.stop()


# -------------------------------------------------------- endpoint contracts


def test_engine_perf_endpoint_contract(params):
    eng = Engine(params, CFG, _ec())
    srv = ModelServer([JetStreamModel("m", engine=eng)])
    srv.start()
    try:
        body = json.dumps({"text_input": "perf contract",
                           "parameters": {"max_tokens": 6}}).encode()
        urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v2/models/m/generate",
            data=body, method="POST")).read()
        snap = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/engine/perf").read())
        rec = snap["models"]["m"]
        for key in ("platform", "peak_flops", "mfu", "goodput_ratio",
                    "dispatched_flops", "goodput_flops", "waste_flops",
                    "cache", "timeline", "profiler", "accounted_flops"):
            assert key in rec, key
        _assert_invariant(rec)
        cache = rec["cache"]
        for key in ("lookups", "hit_pages", "miss_pages", "occupancy",
                    "fragmentation", "top_reused_prefixes", "free_pages"):
            assert key in cache, key
        assert 0.0 <= cache["fragmentation"] <= 1.0
    finally:
        eng.stop()
        srv.stop()


def test_profile_endpoint_contract(params, tmp_path):
    eng = Engine(params, CFG, _ec(profile_dir=str(tmp_path / "profs")))
    srv = ModelServer([JetStreamModel("m", engine=eng)])
    srv.start()
    port = srv.port
    gen = json.dumps({"text_input": "profile me",
                      "parameters": {"max_tokens": 4}}).encode()
    try:
        # bad ticks -> 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/engine/profile",
                data=json.dumps({"ticks": 0}).encode(), method="POST"))
        assert ei.value.code == 400
        out = json.loads(urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/engine/profile",
            data=json.dumps({"ticks": 2}).encode(),
            method="POST")).read())
        assert out["started"] and out["model"] == "m"
        assert out["dir"].startswith(str(tmp_path / "profs"))
        # a second capture while one is armed -> 409
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/engine/profile",
                data=json.dumps({"ticks": 2}).encode(), method="POST"))
        assert ei.value.code == 409
        urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/v2/models/m/generate",
            data=gen, method="POST")).read()
        _wait(lambda: not eng.profiler_active, msg="profiler stop")
        snap = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/engine/perf").read())
        prof = snap["models"]["m"]["profiler"]
        assert prof["captures"] == 1 and not prof["active"]
        assert prof["runs"] and prof["runs"][0]["state"] == "complete"
        assert prof["runs"][0]["nbytes"] > 0
        managed_dir = prof["runs"][0]["dir"]
        assert os.path.isdir(managed_dir)
    finally:
        eng.stop()
        srv.stop()
    # stop() cleans managed capture dirs — profiles must not accumulate
    # across engine lifecycles
    assert not os.path.exists(managed_dir)


def test_profile_refused_on_stopped_engine(params):
    eng = Engine(params, CFG, _ec())
    eng.start()
    eng.stop()
    # arming a capture on a dead loop would wedge profiler_active True
    # forever and leak a managed dir past the stop()-time cleanup
    with pytest.raises(RuntimeError):
        eng.trace_n_ticks(2)
    assert not eng.profiler_active
    assert eng.profiles.snapshot() == []


def test_profile_store_caps_and_cleanup(tmp_path):
    store = ProfileStore(parent=str(tmp_path / "p"), max_runs=2,
                         max_bytes=10**9)
    dirs = []
    for i in range(4):
        d = store.new_dir()
        with open(os.path.join(d, "trace.bin"), "wb") as f:
            f.write(b"x" * 128)
        rec = store.begin(d, 1, managed=True)
        store.complete(rec)
        dirs.append(d)
    # count cap: the two oldest capture dirs are gone, newest two remain
    assert not os.path.exists(dirs[0]) and not os.path.exists(dirs[1])
    assert os.path.isdir(dirs[2]) and os.path.isdir(dirs[3])
    assert len(store.runs) == 2
    # byte cap evicts even under the count cap
    store2 = ProfileStore(parent=str(tmp_path / "q"), max_runs=10,
                          max_bytes=300)
    d2 = []
    for i in range(3):
        d = store2.new_dir()
        with open(os.path.join(d, "trace.bin"), "wb") as f:
            f.write(b"y" * 200)
        rec = store2.begin(d, 1, managed=True)
        store2.complete(rec)
        d2.append(d)
    assert not os.path.exists(d2[0])
    # explicit caller-owned dirs are recorded but never deleted
    own = tmp_path / "mine"
    own.mkdir()
    rec = store2.begin(str(own), 1, managed=False)
    store2.complete(rec)
    store2.close()
    assert own.is_dir()
    assert not os.path.exists(d2[1]) and not os.path.exists(d2[2])


# ------------------------------------------------------------ fleet surfaces


def _mk_service(api, name, svc_port):
    api.create({
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": name, "labels": {LABEL_ISVC: name},
                     "annotations": {PROXY_PORT_ANNOTATION: str(svc_port)}},
        "spec": {"selector": {"app": name}}})


def _mk_pod(api, name, app, port):
    api.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "labels": {"app": app},
                     "annotations": {POD_PORT_ANNOTATION: str(port)}},
        "spec": {},
        "status": {"phase": "Running",
                   "conditions": [{"type": "Ready", "status": "True"}]}})


def _mk_perf_fleet(params, n):
    api = APIServer()
    proxy = ServiceProxy(api)
    svc_port = find_free_ports(1)[0]
    _mk_service(api, "fleet", svc_port)
    engines, servers = [], []
    for i in range(n):
        eng = Engine(params, CFG, _ec())
        srv = ModelServer([JetStreamModel("fleet", "", engine=eng)], port=0)
        srv.start()
        _mk_pod(api, f"fleet-{i}", "fleet", srv.port)
        engines.append(eng)
        servers.append(srv)
    proxy.sync()
    return api, proxy, svc_port, engines, servers


def test_fleet_cache_view_and_pruning_on_pod_churn(params):
    api, proxy, svc_port, engines, servers = _mk_perf_fleet(params, 2)
    try:
        for srv in servers:
            body = json.dumps({"text_input": "warm the cache",
                               "parameters": {"max_tokens": 4}}).encode()
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v2/models/fleet/generate",
                data=body, method="POST")).read()
        view = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{svc_port}/fleet/cache", timeout=30).read())
        assert sorted(view["replicas"]) == ["fleet-0", "fleet-1"]
        rec = view["replicas"]["fleet-0"]["models"]["fleet"]
        assert "cache" in rec and "mfu" in rec and "goodput_ratio" in rec
        assert rec["cache"]["lookups"] >= 1
        assert not view["replicas_unreachable"]
        # pod churn: a deleted replica must not haunt the view
        api.delete("Pod", "fleet-1")
        view = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{svc_port}/fleet/cache", timeout=30).read())
        assert sorted(view["replicas"]) == ["fleet-0"]
        # an unreachable-but-present replica serves its last-known view,
        # marked stale with its age
        servers[0].stop()
        view = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{svc_port}/fleet/cache", timeout=30).read())
        assert view["replicas_unreachable"] == ["fleet-0"]
        assert view["replicas"]["fleet-0"]["stale"] is True
        assert view["replicas"]["fleet-0"]["age_s"] >= 0
    finally:
        proxy.shutdown()
        for srv in servers:
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — already stopped above
                pass
        for eng in engines:
            eng.stop(drain=False)


def test_fleet_metrics_scrape_latency_header(params):
    api, proxy, svc_port, engines, servers = _mk_perf_fleet(params, 2)
    try:
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{svc_port}/fleet/metrics", timeout=30
        ).read().decode()
        lat_lines = [ln for ln in text.splitlines()
                     if ln.startswith("# scrape_seconds: ")]
        assert len(lat_lines) == 1
        entries = dict(kv.split("=") for kv in
                       lat_lines[0][len("# scrape_seconds: "):].split(","))
        assert sorted(entries) == ["fleet-0", "fleet-1"]
        for v in entries.values():
            assert float(v) >= 0.0
        # a dead replica still reports the latency it burned (the slow-vs-
        # dead distinction the header exists for)
        servers[1].stop()
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{svc_port}/fleet/metrics", timeout=30
        ).read().decode()
        assert "unreachable: fleet-1" in text.splitlines()[0]
        assert any(ln.startswith("# scrape_seconds: ") and "fleet-1=" in ln
                   for ln in text.splitlines())
    finally:
        proxy.shutdown()
        for srv in servers:
            try:
                srv.stop()
            except Exception:  # noqa: BLE001
                pass
        for eng in engines:
            eng.stop(drain=False)


# ----------------------------------------------------------- metric exposition


def test_perf_metric_exposition(params):
    eng = Engine(params, CFG, _ec())
    eng.start()
    m = JetStreamModel("m", engine=eng)
    try:
        eng.generate(list(range(1, 15)), 6)
        eng.generate(list(range(1, 15)), 6)  # cache hit -> hit outcome
        text = m.metrics_text()
        mfu_lines = [ln for ln in text.splitlines()
                     if ln.startswith("engine_mfu_ratio{")]
        assert len(mfu_lines) == 1
        assert 'platform="' in mfu_lines[0] and 'model="m"' in mfu_lines[0]
        assert "engine_goodput_ratio" in text
        assert "engine_kv_fragmentation_ratio" in text
        assert 'engine_model_flops_total{kind="prefill"' in text
        assert 'engine_model_flops_total{kind="decode"' in text
        assert 'engine_prefix_cache_pages_total{outcome="hit",model="m"}' \
            in text
        # the counters mirror the ledger exactly (same charge path)
        snap = eng.perf_snapshot()
        for kind, val in snap["flops_by_kind"].items():
            if val:
                assert eng.telemetry.flops_total.value(kind=kind) \
                    == pytest.approx(val)
        for reason, val in snap["waste_flops"].items():
            assert eng.telemetry.wasted_flops.value(reason=reason) \
                == pytest.approx(val)
    finally:
        eng.stop()
